"""Health-filtered host sets: circuit breakers and active monitors.

Mirrors uber/kraken ``lib/healthcheck`` (``Monitor``: periodic health
endpoint probing with pass/fail thresholds; ``PassiveFilter``:
mark-bad-on-request-error with cooldown) -- upstream path, unverified;
SURVEY.md SS2.3/SS5 -- evolved into a closed/open/half-open circuit
breaker (round 8, the overload & degradation plane):

- **closed**: requests flow; consecutive failures count (a streak older
  than the cooldown decays -- sporadic faults on a low-traffic host must
  not accumulate forever).
- **open**: >= ``fail_threshold`` consecutive failures trip the host out
  of rotation until the cooldown passes. A probe failure re-opens with
  DECORRELATED-JITTER backoff (utils/backoff.DecorrelatedJitter) so a
  flapping host's re-probes across a fleet never synchronize.
- **half-open**: after the cooldown the host admits EXACTLY ONE probe
  request (:meth:`try_acquire_probe`); success closes the breaker,
  failure re-opens it with a longer cooldown. Concurrent callers that
  lose the probe race skip to the next replica instead of piling onto a
  host that just proved unreliable.

Brown-outs (slow-but-ALIVE hosts -- the tail-latency killer a binary
up/down model cannot see) are tracked by a per-host latency EWMA
(:meth:`observe`): a closed host whose EWMA exceeds
``brownout_threshold_seconds`` is not opened (it still works!) but sheds
to the BACK of the replica order (:meth:`order`), where hedged reads
(origin/client.py) only reach it if the fast replicas fail.

Verdicts are visible: gauges ``breaker_state{host}`` (0 closed / 1
half-open / 2 open), ``host_latency_ewma_seconds{host}``, and
``healthcheck_unhealthy_hosts{source}``, plus ``GET /debug/healthcheck``
on every metrics mux (utils/metrics.py) rendering :func:`debug_snapshot`
-- "why is this replica being skipped" must never require a debugger.

Feeds the hashring: dead origins leave the ring, and their blobs
re-place onto the survivors.
"""

from __future__ import annotations

import asyncio
import itertools
import threading
import time
import weakref
from typing import Awaitable, Callable, Iterable

from kraken_tpu.utils.backoff import DecorrelatedJitter
from kraken_tpu.utils.metrics import REGISTRY

# Breaker states (also the ``breaker_state{host}`` gauge values).
CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half_open", OPEN: "open"}

# Every live filter/monitor, for the /debug/healthcheck mux. Weak so the
# short-lived instances tests and ad-hoc clients create never accumulate.
_instances: "weakref.WeakSet" = weakref.WeakSet()
_name_seq = itertools.count()
_instances_lock = threading.Lock()


def debug_snapshot() -> dict:
    """Everything every live health filter knows, keyed by instance name
    (the operator's "why is this replica skipped" surface)."""
    with _instances_lock:
        insts = list(_instances)
    return {inst.name: inst.snapshot() for inst in insts}


def _register(inst) -> None:
    with _instances_lock:
        _instances.add(inst)


class _ProbeToken(str):
    """The half-open probe token: compares equal to ``"probe"`` (API
    compatibility) but each grant is a DISTINCT object, so a release can
    be matched to ITS grant -- a stale release from a cancelled holder
    must never free a token a later caller has since acquired."""

    __slots__ = ()


class _HostState:
    __slots__ = (
        "state", "fails", "open_until", "backoff_prev", "probe_inflight",
        "ewma", "last_fail",
    )

    def __init__(self):
        self.state = CLOSED
        self.fails = 0
        self.open_until = 0.0
        self.backoff_prev = 0.0  # DecorrelatedJitter carry (0 = untripped)
        self.probe_inflight: _ProbeToken | None = None
        self.ewma = 0.0  # success-latency EWMA, seconds (0 = no sample yet)
        self.last_fail = 0.0


class PassiveFilter:
    """Callers report request outcomes; the breaker decides who gets
    traffic. Backwards-compatible surface (``failed`` / ``succeeded`` /
    ``healthy`` / ``filter`` / ``prune``) plus the breaker/brown-out API
    (``observe`` / ``try_acquire_probe`` / ``order``).

    ``healthy()`` is the MEMBERSHIP view (ring filtering): an open host
    past its cooldown reads healthy again so the ring re-admits it --
    but the first request it then receives is the half-open probe, so
    "un-ban after cooldown" no longer means "full traffic, no
    evidence"."""

    def __init__(
        self,
        fail_threshold: int = 3,
        cooldown_seconds: float = 30.0,
        max_cooldown_seconds: float = 300.0,
        brownout_threshold_seconds: float = 0.0,
        ewma_alpha: float = 0.3,
        name: str = "",
    ):
        self.fail_threshold = fail_threshold
        self.cooldown = cooldown_seconds
        self.brownout_threshold = brownout_threshold_seconds
        self.ewma_alpha = ewma_alpha
        self.name = name or f"passive-{next(_name_seq)}"
        self._jitter = DecorrelatedJitter(
            base_seconds=cooldown_seconds,
            max_seconds=max(cooldown_seconds, max_cooldown_seconds),
        )
        # Named `_fails` since the pre-breaker builds: external eyes
        # (tests, debuggers) read its KEYS as "hosts with recorded
        # trouble"; values are full breaker records now.
        self._fails: dict[str, _HostState] = {}
        self._state_gauge = REGISTRY.gauge(
            "breaker_state",
            "Per-host circuit state: 0 closed, 1 half-open, 2 open",
        )
        self._ewma_gauge = REGISTRY.gauge(
            "host_latency_ewma_seconds",
            "Per-host EWMA of successful-request latency",
        )
        self._unhealthy_gauge = REGISTRY.gauge(
            "healthcheck_unhealthy_hosts",
            "Hosts a health filter currently holds out of (or shed to the"
            " back of) rotation, by filter instance",
        )
        _register(self)

    # -- outcome reporting -------------------------------------------------

    def observe(self, host: str, ok: bool, seconds: float | None = None,
                now: float | None = None) -> None:
        """One request outcome with its latency: the single entry point
        request paths should use (``succeeded``/``failed`` remain for
        callers with no latency to report). Only SUCCESS latencies feed
        the brown-out EWMA: a fast connection-refused would drag a truly
        browned-out host's average toward zero, and a timeout-bound
        failure would pin it sky-high long after recovery -- failures
        already speak through the breaker itself."""
        if ok and seconds is not None:
            s = self._get(host)
            s.ewma = (
                seconds if s.ewma == 0.0
                else (1 - self.ewma_alpha) * s.ewma + self.ewma_alpha * seconds
            )
            self._ewma_gauge.set(s.ewma, host=host)
        if ok:
            self.succeeded(host)
        else:
            self.failed(host, now=now)

    def failed(self, host: str, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        s = self._get(host)
        if s.state == HALF_OPEN:
            # The probe itself failed: straight back to open, with a
            # longer (decorrelated-jitter) cooldown than last time.
            s.probe_inflight = None
            self._open(s, now, host)
        else:
            if s.fails and now - s.last_fail > self.cooldown:
                s.fails = 0  # stale streak: sporadic faults don't add up
            s.fails += 1
            if s.state == CLOSED and s.fails >= self.fail_threshold:
                self._open(s, now, host)
        s.last_fail = now
        self._publish(host, s)

    def succeeded(self, host: str) -> None:
        s = self._fails.get(host)
        if s is None:
            return
        s.state = CLOSED
        s.fails = 0
        s.probe_inflight = None
        s.backoff_prev = 0.0
        if s.ewma == 0.0:
            # Nothing left worth remembering: drop the record so the map
            # only holds hosts with live trouble or latency history.
            del self._fails[host]
        self._publish(host, s if host in self._fails else None)

    def _open(self, s: _HostState, now: float, host: str = "") -> None:
        s.state = OPEN
        s.backoff_prev = self._jitter.next(s.backoff_prev)
        s.open_until = now + s.backoff_prev
        s.fails = 0
        # A breaker trip is a degradation event: persist the flight
        # recorder NOW (throttled, never raises) -- the spans that led
        # here are the postmortem, and they age out of the ring fast.
        from kraken_tpu.utils.trace import TRACER

        TRACER.trigger_dump(
            "breaker_trip", f"{self.name}: {host or 'unknown host'}"
        )

    # -- admission ---------------------------------------------------------

    def healthy(self, host: str, now: float | None = None) -> bool:
        """Membership view (ring filter): open-and-cooling reads False;
        everything else -- closed, half-open, open past its cooldown --
        reads True (eligible for traffic; request admission is the
        probe gate's job)."""
        now = time.monotonic() if now is None else now
        s = self._fails.get(host)
        if s is None or s.state != OPEN:
            return True
        return now >= s.open_until

    def try_acquire_probe(self, host: str, now: float | None = None):
        """Request admission. Closed hosts always admit (``True``). An
        open host past its cooldown transitions to half-open and admits
        EXACTLY one caller -- that caller gets a truthy probe token
        (``== "probe"``; release via :meth:`release_probe` if the
        request is abandoned); everyone else gets ``False`` and goes
        elsewhere until the probe's outcome reports back."""
        now = time.monotonic() if now is None else now
        s = self._fails.get(host)
        if s is None or s.state == CLOSED:
            return True
        if s.state == OPEN:
            if now < s.open_until:
                return False
            s.state = HALF_OPEN
            s.probe_inflight = _ProbeToken("probe")
            self._publish(host, s)
            return s.probe_inflight
        # HALF_OPEN: one probe at a time.
        if s.probe_inflight is not None:
            return False
        s.probe_inflight = _ProbeToken("probe")
        return s.probe_inflight

    def release_probe(self, host: str, token=None) -> None:
        """A probe holder that never issued its request (cancelled
        hedge, shutdown) must hand the token back or the host starves.
        With ``token`` the release applies only if THAT grant is still
        the live one -- a stale release from a cancelled holder must not
        free a token a later caller has since acquired."""
        s = self._fails.get(host)
        if s is None or s.state != HALF_OPEN:
            return
        if token is None or s.probe_inflight is token:
            s.probe_inflight = None

    def browned_out(self, host: str) -> bool:
        if self.brownout_threshold <= 0:
            return False
        s = self._fails.get(host)
        return s is not None and s.ewma > self.brownout_threshold

    # -- set views ---------------------------------------------------------

    def filter(self, hosts: Iterable[str], now: float | None = None) -> list[str]:
        out = [h for h in hosts if self.healthy(h, now)]
        # All-unhealthy degrades to all-in (serving badly beats serving
        # nothing, as in the reference).
        return out or list(hosts)

    def order(self, hosts: Iterable[str], now: float | None = None) -> list[str]:
        """Replica-walk order for reads: healthy and probe-eligible
        hosts keep their placement order -- the probe must FLOW with
        normal traffic or a recovered host would stay demoted forever,
        and the admission gate already bounds its exposure to exactly
        one request. Browned-out hosts shed to the back of the healthy
        set; hard-open (still cooling) hosts go last but are never
        dropped -- with everyone unhealthy they are still the only place
        the bytes live."""
        now = time.monotonic() if now is None else now

        def tier(h: str) -> int:
            s = self._fails.get(h)
            if s is None:
                return 0
            if s.state == OPEN and now < s.open_until:
                return 2
            return 1 if self.browned_out(h) else 0

        return sorted(hosts, key=tier)  # stable: placement order within tiers

    def unhealthy_hosts(self, now: float | None = None) -> set[str]:
        """Hosts currently out of (or shed to the back of) rotation --
        the set the tracker's peer handout de-prioritizes."""
        now = time.monotonic() if now is None else now
        return {
            h for h, s in self._fails.items()
            if s.state != CLOSED or self.browned_out(h)
        }

    def prune(self, current_hosts: Iterable[str]) -> int:
        """Forget hosts that left the hostlist. Without this the state
        map grows without bound under membership churn (k8s pod cycling
        mints a fresh ip:port per generation) and a departed host's stale
        verdict would apply to a REUSED address the moment it comes back.
        Called from the assembly refresh tick. Returns entries dropped."""
        keep = set(current_hosts)
        stale = [h for h in self._fails if h not in keep]
        for h in stale:
            del self._fails[h]
            self._publish(h, None)
        return len(stale)

    # -- introspection -----------------------------------------------------

    def _get(self, host: str) -> _HostState:
        s = self._fails.get(host)
        if s is None:
            s = self._fails[host] = _HostState()
        return s

    def _publish(self, host: str, s: _HostState | None) -> None:
        self._state_gauge.set(s.state if s is not None else CLOSED, host=host)
        self._unhealthy_gauge.set(len(self.unhealthy_hosts()), source=self.name)

    def snapshot(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        return {
            "kind": "breaker",
            "fail_threshold": self.fail_threshold,
            "cooldown_seconds": self.cooldown,
            "brownout_threshold_seconds": self.brownout_threshold,
            "hosts": {
                h: {
                    "state": _STATE_NAMES[s.state],
                    "consecutive_fails": s.fails,
                    "open_for_seconds": round(max(0.0, s.open_until - now), 3),
                    "probe_inflight": s.probe_inflight is not None,
                    "latency_ewma_seconds": round(s.ewma, 4),
                    "browned_out": self.browned_out(h),
                }
                for h, s in sorted(self._fails.items())
            },
        }


class ActiveMonitor:
    """Periodic probe of every host; tracks consecutive pass/fail counts.

    ``probe`` is an async callable (host) -> bool. Drive :meth:`check_all`
    from a service timer task; ``healthy_hosts`` reflects the latest state.
    """

    def __init__(
        self,
        probe: Callable[[str], Awaitable[bool]],
        pass_threshold: int = 1,
        fail_threshold: int = 3,
        name: str = "",
    ):
        self._probe = probe
        self.pass_threshold = pass_threshold
        self.fail_threshold = fail_threshold
        self.name = name or f"active-{next(_name_seq)}"
        # host -> (healthy verdict, consecutive contrary results)
        self._state: dict[str, tuple[bool, int]] = {}
        self._unhealthy_gauge = REGISTRY.gauge(
            "healthcheck_unhealthy_hosts",
            "Hosts a health filter currently holds out of (or shed to the"
            " back of) rotation, by filter instance",
        )
        _register(self)

    async def check_all(self, hosts: Iterable[str]) -> None:
        hosts = list(hosts)

        async def probe(h: str) -> bool:
            try:
                return await self._probe(h)
            except Exception:
                return False

        # Concurrent probes: detection latency is one probe timeout, not
        # cluster_size timeouts (serial probing of a large ring with dead
        # peers would exceed the check interval itself).
        results = await asyncio.gather(*(probe(h) for h in hosts))
        for h, ok in zip(hosts, results):
            healthy, contrary = self._state.get(h, (True, 0))
            if ok == healthy:
                contrary = 0
            else:
                contrary += 1
                threshold = self.pass_threshold if ok else self.fail_threshold
                if contrary >= threshold:
                    healthy, contrary = ok, 0
            self._state[h] = (healthy, contrary)
        self._publish()

    def healthy(self, host: str) -> bool:
        return self._state.get(host, (True, 0))[0]

    def filter(self, hosts: Iterable[str]) -> list[str]:
        out = [h for h in hosts if self.healthy(h)]
        return out or list(hosts)

    def prune(self, current_hosts: Iterable[str]) -> int:
        """Forget verdicts for hosts no longer in the hostlist (same
        unbounded-growth and stale-verdict hazard as
        :meth:`PassiveFilter.prune`; a host re-added later starts fresh
        at the healthy default). Returns entries dropped."""
        keep = set(current_hosts)
        stale = [h for h in self._state if h not in keep]
        for h in stale:
            del self._state[h]
        self._publish()
        return len(stale)

    def _publish(self) -> None:
        self._unhealthy_gauge.set(
            sum(1 for v, _c in self._state.values() if not v),
            source=self.name,
        )

    def snapshot(self, now: float | None = None) -> dict:
        return {
            "kind": "active_monitor",
            "pass_threshold": self.pass_threshold,
            "fail_threshold": self.fail_threshold,
            "hosts": {
                h: {"healthy": v, "consecutive_contrary": c}
                for h, (v, c) in sorted(self._state.items())
            },
        }

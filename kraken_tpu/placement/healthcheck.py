"""Health-filtered host sets: active monitors and passive filters.

Mirrors uber/kraken ``lib/healthcheck`` (``Monitor``: periodic health
endpoint probing with pass/fail thresholds; ``PassiveFilter``:
mark-bad-on-request-error with cooldown) -- upstream path, unverified;
SURVEY.md SS2.3/SS5. Feeds the hashring: dead origins leave the ring, and
their blobs re-place onto the survivors.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Iterable


class PassiveFilter:
    """Callers report request failures; hosts with >= ``fail_threshold``
    recent failures are filtered out until ``cooldown_seconds`` pass."""

    def __init__(self, fail_threshold: int = 3, cooldown_seconds: float = 30.0):
        self.fail_threshold = fail_threshold
        self.cooldown = cooldown_seconds
        self._fails: dict[str, list[float]] = {}

    def failed(self, host: str, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self._fails.setdefault(host, []).append(now)

    def succeeded(self, host: str) -> None:
        self._fails.pop(host, None)

    def healthy(self, host: str, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        fails = self._fails.get(host)
        if not fails:
            return True
        recent = [t for t in fails if now - t < self.cooldown]
        self._fails[host] = recent
        return len(recent) < self.fail_threshold

    def filter(self, hosts: Iterable[str], now: float | None = None) -> list[str]:
        out = [h for h in hosts if self.healthy(h, now)]
        # All-unhealthy degrades to all-in (serving badly beats serving
        # nothing, as in the reference).
        return out or list(hosts)

    def prune(self, current_hosts: Iterable[str]) -> int:
        """Forget hosts that left the hostlist. Without this the failure
        map grows without bound under membership churn (k8s pod cycling
        mints a fresh ip:port per generation) and a departed host's stale
        verdict would apply to a REUSED address the moment it comes back.
        Called from the assembly refresh tick. Returns entries dropped."""
        keep = set(current_hosts)
        stale = [h for h in self._fails if h not in keep]
        for h in stale:
            del self._fails[h]
        return len(stale)


class ActiveMonitor:
    """Periodic probe of every host; tracks consecutive pass/fail counts.

    ``probe`` is an async callable (host) -> bool. Drive :meth:`check_all`
    from a service timer task; ``healthy_hosts`` reflects the latest state.
    """

    def __init__(
        self,
        probe: Callable[[str], Awaitable[bool]],
        pass_threshold: int = 1,
        fail_threshold: int = 3,
    ):
        self._probe = probe
        self.pass_threshold = pass_threshold
        self.fail_threshold = fail_threshold
        # host -> (healthy verdict, consecutive contrary results)
        self._state: dict[str, tuple[bool, int]] = {}

    async def check_all(self, hosts: Iterable[str]) -> None:
        hosts = list(hosts)

        async def probe(h: str) -> bool:
            try:
                return await self._probe(h)
            except Exception:
                return False

        # Concurrent probes: detection latency is one probe timeout, not
        # cluster_size timeouts (serial probing of a large ring with dead
        # peers would exceed the check interval itself).
        results = await asyncio.gather(*(probe(h) for h in hosts))
        for h, ok in zip(hosts, results):
            healthy, contrary = self._state.get(h, (True, 0))
            if ok == healthy:
                contrary = 0
            else:
                contrary += 1
                threshold = self.pass_threshold if ok else self.fail_threshold
                if contrary >= threshold:
                    healthy, contrary = ok, 0
            self._state[h] = (healthy, contrary)

    def healthy(self, host: str) -> bool:
        return self._state.get(host, (True, 0))[0]

    def filter(self, hosts: Iterable[str]) -> list[str]:
        out = [h for h in hosts if self.healthy(h)]
        return out or list(hosts)

    def prune(self, current_hosts: Iterable[str]) -> int:
        """Forget verdicts for hosts no longer in the hostlist (same
        unbounded-growth and stale-verdict hazard as
        :meth:`PassiveFilter.prune`; a host re-added later starts fresh
        at the healthy default). Returns entries dropped."""
        keep = set(current_hosts)
        stale = [h for h in self._state if h not in keep]
        for h in stale:
            del self._state[h]
        return len(stale)

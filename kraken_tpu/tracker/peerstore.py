"""Peer membership per info-hash, with TTL expiry.

Mirrors uber/kraken ``tracker/peerstore`` (Redis SETEX-style TTL records;
dead agents vanish from handouts when their announces stop) -- upstream
path, unverified; SURVEY.md SS2.4/SS5. The production reference needs an
external Redis; here the default is an in-process TTL dict behind the same
interface (this environment has no Redis server; the seam stays so a
redis-protocol store can drop in).
"""

from __future__ import annotations

import time

from kraken_tpu.core.peer import PeerInfo


class PeerStore:
    """Interface: update a peer's announce record, list live peers."""

    def update(self, info_hash: str, peer: PeerInfo) -> None:
        raise NotImplementedError

    def get_peers(self, info_hash: str, limit: int = 50) -> list[PeerInfo]:
        raise NotImplementedError


class InMemoryPeerStore(PeerStore):
    def __init__(self, ttl_seconds: float = 30.0):
        self.ttl = ttl_seconds
        # info_hash -> peer_id hex -> (expiry, PeerInfo)
        self._swarms: dict[str, dict[str, tuple[float, PeerInfo]]] = {}

    def update(self, info_hash: str, peer: PeerInfo, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        swarm = self._swarms.setdefault(info_hash, {})
        swarm[peer.peer_id.hex] = (now + self.ttl, peer)

    def get_peers(
        self, info_hash: str, limit: int = 50, now: float | None = None
    ) -> list[PeerInfo]:
        now = time.monotonic() if now is None else now
        swarm = self._swarms.get(info_hash)
        if not swarm:
            return []
        for pid, (expiry, _p) in list(swarm.items()):
            if expiry <= now:
                del swarm[pid]
        return [p for _e, p in swarm.values()][:limit]

"""Peer membership per info-hash, with TTL expiry.

Mirrors uber/kraken ``tracker/peerstore`` (Redis SETEX-style TTL records;
dead agents vanish from handouts when their announces stop) -- upstream
path, unverified; SURVEY.md SS2.4/SS5. Two implementations behind one
async interface:

- :class:`InMemoryPeerStore` -- per-process TTL dict (default; tracker
  state dies with the process, TTL re-heals the swarm on restart).
- :class:`RedisPeerStore` -- speaks RESP to a real Redis (or compatible)
  server, stdlib-only, so tracker restarts keep the swarm and multiple
  trackers can share one store. One HASH per swarm (``swarm:<info_hash>``,
  field = peer id, value = peer json with an embedded absolute expiry), so
  reads are O(swarm size), never O(keyspace); the whole key gets EXPIREd
  on every announce so idle swarms vanish from Redis wholesale, and
  per-peer expiry is enforced on read from the embedded timestamp (with
  lazy HDEL of the dead fields).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from typing import Optional

from kraken_tpu.core.peer import PeerInfo
from kraken_tpu.utils.metrics import REGISTRY, FailureMeter

_log = logging.getLogger("kraken.tracker.peerstore")


class PeerStore:
    """Interface: record a peer's announce, list live peers."""

    async def update(self, info_hash: str, peer: PeerInfo) -> None:
        raise NotImplementedError

    async def get_peers(self, info_hash: str, limit: int = 50) -> list[PeerInfo]:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class InMemoryPeerStore(PeerStore):
    # Amortized sweep cadence: every N updates, expire-scan EVERY swarm.
    # Per-swarm pruning in get_peers only reaps hashes someone still asks
    # about; a tracker serving many one-shot torrents accumulates dead
    # swarms nobody will ever query again.
    _SWEEP_EVERY = 1024

    def __init__(self, ttl_seconds: float = 30.0):
        self.ttl = ttl_seconds
        # info_hash -> peer_id hex -> (expiry, PeerInfo)
        self._swarms: dict[str, dict[str, tuple[float, PeerInfo]]] = {}
        self._updates = 0

    async def update(
        self, info_hash: str, peer: PeerInfo, now: float | None = None
    ) -> None:
        now = time.monotonic() if now is None else now
        swarm = self._swarms.setdefault(info_hash, {})
        swarm[peer.peer_id.hex] = (now + self.ttl, peer)
        self._updates += 1
        if self._updates % self._SWEEP_EVERY == 0:
            self._sweep(now)

    def _sweep(self, now: float) -> None:
        for h, swarm in list(self._swarms.items()):
            for pid, (expiry, _p) in list(swarm.items()):
                if expiry <= now:
                    del swarm[pid]
            if not swarm:
                del self._swarms[h]

    async def get_peers(
        self, info_hash: str, limit: int = 50, now: float | None = None
    ) -> list[PeerInfo]:
        now = time.monotonic() if now is None else now
        swarm = self._swarms.get(info_hash)
        if not swarm:
            return []
        for pid, (expiry, _p) in list(swarm.items()):
            if expiry <= now:
                del swarm[pid]
        if not swarm:
            # Drop the emptied swarm entry: a tracker serving many
            # one-shot torrents would otherwise grow without bound.
            del self._swarms[info_hash]
            return []
        if len(swarm) <= limit:
            return [p for _e, p in swarm.values()]
        # SAMPLE, don't slice: insertion order hands every announcer the
        # same first-N peers, and in a large swarm those N saturate while
        # everyone else starves (measured: the 10k-agent sim could not
        # complete before this). Random sampling is also the reference
        # peerstore's behavior.
        return [
            swarm[k][1] for k in random.sample(list(swarm), limit)
        ]


class RespError(Exception):
    """Server-side RESP error reply."""


class _RespConn:
    """One RESP connection: encode commands, decode replies."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    @staticmethod
    def _encode(args) -> bytes:
        out = [f"*{len(args)}\r\n".encode()]
        for a in args:
            if isinstance(a, int):
                a = str(a)
            if isinstance(a, str):
                a = a.encode()
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        return b"".join(out)

    async def command(self, *args: str | bytes | int):
        self.writer.write(self._encode(args))
        await self.writer.drain()
        return await self._read_reply()

    async def pipeline(self, *commands):
        """Send several commands in one write, read all replies -- one RTT
        instead of len(commands). EVERY reply is consumed before a server
        error is raised: bailing on the first -ERR would leave the later
        replies in the stream and desync every subsequent command by one."""
        self.writer.write(b"".join(self._encode(c) for c in commands))
        await self.writer.drain()
        replies = []
        first_err: RespError | None = None
        for _ in commands:
            try:
                replies.append(await self._read_reply())
            except RespError as e:
                if first_err is None:
                    first_err = e
                replies.append(e)
        if first_err is not None:
            raise first_err
        return replies

    async def _read_reply(self):
        line = (await self.reader.readline()).rstrip(b"\r\n")
        if not line:
            raise ConnectionError("redis connection closed")
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = await self.reader.readexactly(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            # Same consume-everything rule for nested error elements.
            items = []
            first_err: RespError | None = None
            for _ in range(n):
                try:
                    items.append(await self._read_reply())
                except RespError as e:
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
            return items
        # Unknown type byte = protocol garbage, not a server error reply:
        # the stream position is unknowable (ValueError -> conn invalidated
        # by the caller), unlike a clean "-ERR ..." RespError.
        raise ValueError(f"unparseable RESP reply type {kind!r}")

    def close(self) -> None:
        self.writer.close()


class RedisPeerStore(PeerStore):
    """Swarm records in a Redis-protocol server (one conn, serialized by a
    lock -- announce volume is paced by the announce queue upstream)."""

    def __init__(
        self,
        addr: str,
        ttl_seconds: float = 30.0,
        timeout_seconds: float = 5.0,
    ):
        host, _, port = addr.rpartition(":")
        self.host, self.port = host, int(port)
        self.ttl = max(1, int(ttl_seconds))
        # Per-command deadline: a blackholed Redis must fail announces
        # fast (500s the swarm can retry), not wedge every handler behind
        # the connection lock forever.
        self.timeout = timeout_seconds
        self._conn: Optional[_RespConn] = None
        self._lock = asyncio.Lock()
        # A dropped/desynced store conn is a reconnect, not an outage:
        # visible on /metrics so a flapping Redis is diagnosable before
        # it becomes announce 500s.
        self._reconnects = REGISTRY.counter(
            "redis_peerstore_reconnects_total",
            "Redis peerstore connections invalidated (timeout, EOF,"
            " protocol garbage) and rebuilt on the next attempt",
        )
        self._errors = FailureMeter(
            "redis_peerstore_errors_total",
            "Redis peerstore operations that failed after the reconnect"
            " retry (the announce handler 500s and the swarm retries)",
            _log,
        )

    async def _get_conn(self) -> _RespConn:
        if self._conn is None:
            reader, writer = await asyncio.open_connection(self.host, self.port)
            self._conn = _RespConn(reader, writer)
        return self._conn

    async def _run(self, op):
        """Run ``op(conn)`` with a deadline and a single reconnect retry.
        ANY failed attempt -- including the retry -- invalidates the
        connection: a timed-out command leaves the stream mid-frame, and
        reusing it would desync every later reply by one."""
        async with self._lock:
            for attempt in (0, 1):
                try:
                    conn = await self._get_conn()
                    return await asyncio.wait_for(op(conn), self.timeout)
                except RespError:
                    # A clean server error reply ("-ERR ..."): the stream
                    # is still in sync -- the conn stays; the error is
                    # the caller's to handle.
                    raise
                except (ConnectionError, OSError,
                        asyncio.IncompleteReadError, asyncio.TimeoutError,
                        ValueError) as e:
                    # IncompleteReadError is an EOFError, not a
                    # ConnectionError: the server died mid-reply.
                    # ValueError = unparseable reply bytes (protocol
                    # garbage): the stream position is unknowable, so the
                    # conn must not be reused either.
                    if self._conn is not None:
                        self._conn.close()
                    self._conn = None
                    self._reconnects.inc()
                    if attempt:
                        self._errors.record(
                            f"redis {self.host}:{self.port}", e
                        )
                        raise

    async def _cmd(self, *args):
        return await self._run(lambda conn: conn.command(*args))

    @staticmethod
    def _key(info_hash: str) -> str:
        return f"swarm:{info_hash}"

    async def update(self, info_hash: str, peer: PeerInfo) -> None:
        doc = peer.to_dict()
        # Absolute wall-clock expiry: trackers sharing the store are
        # NTP-synced in any deployment where they share a Redis.
        doc["_expiry"] = time.time() + self.ttl
        key = self._key(info_hash)
        # One pipelined round trip; the commands land in Redis's input
        # buffer together, so there is no window where the HSET executed
        # but the EXPIRE (which keeps the swarm key from outliving its
        # announcers) is lost.
        await self._run(lambda conn: conn.pipeline(
            ("HSET", key, peer.peer_id.hex, json.dumps(doc)),
            ("EXPIRE", key, self.ttl),
        ))

    async def get_peers(self, info_hash: str, limit: int = 50) -> list[PeerInfo]:
        reply = await self._cmd("HGETALL", self._key(info_hash))
        if not reply:
            return []
        now = time.time()
        out: list[PeerInfo] = []
        dead: list[bytes] = []
        for field, value in zip(reply[0::2], reply[1::2]):
            try:
                doc = json.loads(value)
                expiry = float(doc.pop("_expiry", 0))
                if expiry <= now:
                    # Lazy reap, with one full TTL of grace: HDEL is not
                    # atomic with the HGETALL snapshot, so a freshly-expired
                    # field might have been re-HSET by a concurrent
                    # announce -- deleting it would drop a live peer until
                    # its next announce. A field dead for a whole extra TTL
                    # has no concurrent announcer in practice.
                    if expiry <= now - self.ttl:
                        dead.append(field)
                    continue
                out.append(PeerInfo.from_dict(doc))
            except (ValueError, KeyError):
                dead.append(field)
        if dead:
            # Best-effort reap: the read already has its answer -- a
            # store hiccup on this housekeeping HDEL must not turn a
            # successful handout into a 500 (the fields stay dead-but-
            # present and the next read retries the reap).
            try:
                await self._cmd("HDEL", self._key(info_hash), *dead)
            except (RespError, ConnectionError, OSError,
                    asyncio.IncompleteReadError, asyncio.TimeoutError,
                    ValueError) as e:
                self._errors.record(
                    f"lazy HDEL {self.host}:{self.port}", e
                )
        if len(out) <= limit:
            return out
        # SAMPLE, not slice: HGETALL field order is stable per key, so a
        # slice hands every announcer the same N peers -- the large-swarm
        # starvation wedge documented in PERF.md (same fix as the
        # in-memory store above).
        return random.sample(out, limit)

    async def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

"""Tracker: peer membership + metainfo proxy.

Mirrors uber/kraken ``tracker/`` (trackerserver announce endpoint,
Redis-backed peerstore with TTL, peerhandoutpolicy, metainfo proxy caching
origin responses) -- upstream paths, unverified; SURVEY.md SS2.4/SS3.4.
"""

"""Tracker HTTP API: announce + metainfo proxy.

Mirrors uber/kraken ``tracker/trackerserver`` (announce endpoint: peer <->
peer-list exchange with an announce interval; metainfo endpoint proxying
the origin cluster with a TTL cache) -- upstream path, unverified;
SURVEY.md SS2.4/SS3.4.

Endpoints:

    POST /announce                 body: announce record   -> {peers, interval}
    GET  /namespace/{ns}/blobs/{d}/metainfo               -> metainfo doc
    GET  /namespace/{ns}/blobs/{d}/recipe                 -> chunk recipe
                                                             (X-Kraken-Origin:
                                                             serving origin)
    GET  /namespace/{ns}/blobs/{d}/similar                -> near-dup list
    GET  /health
    POST/GET /debug/lameduck

Agents know only the tracker, so the delta-transfer control plane
(recipes + /similar) proxies through it exactly like metainfo; the
``X-Kraken-Origin`` header names the origin that served the recipe so
agents can aim byte-range fetches at a replica that actually holds the
blob.

Fleet mode (round 12, the tracker HA plane): trackers run as a
rendezvous-sharded fleet -- clients (``tracker/client.TrackerFleetClient``)
shard announces by info hash so each tracker owns a stable slice, and
fail over along the ring when a tracker dies. Every tracker SERVES any
swarm unconditionally (a peer handout never errors just because the
shard owner died: the local store answers, and with the in-memory store
the failover swarm re-forms within one announce interval as peers
re-announce). A non-owner additionally FORWARDS accepted announces to
the live shard owner (best-effort, throttled, breaker-gated) so mixed
client views during a membership change never lose a registered peer.
Trackers sharing a Redis store skip forwarding -- the store is the
rendezvous point. Lameduck (``enter_lameduck`` / the debug endpoint /
SIGTERM) flips /health to 503 and refuses new announces so rolling
restarts drain one tracker at a time, exactly like agents and origins.
"""

from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse

from aiohttp import web

from kraken_tpu.core.digest import Digest, DigestError
from kraken_tpu.core.peer import PeerInfo
from kraken_tpu.placement.healthcheck import PassiveFilter
from kraken_tpu.placement.hrw import rendezvous_hash
from kraken_tpu.tracker.peerhandout import default_priority
from kraken_tpu.tracker.peerstore import InMemoryPeerStore, PeerStore
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.dedup import TTLCache
from kraken_tpu.utils.httputil import HTTPClient, base_url, is_not_found
from kraken_tpu.utils.lameduck import LameduckMixin
from kraken_tpu.utils.metrics import REGISTRY, FailureMeter

_log = logging.getLogger("kraken.tracker")

# Marks an announce the fleet already forwarded once: the owner must
# never re-forward (membership disagreement between trackers would
# otherwise bounce an announce around the fleet forever).
_FORWARDED_HEADER = "X-Kraken-Forwarded"


class TrackerServer(LameduckMixin):
    lameduck_component = "tracker"

    def __init__(
        self,
        peer_store: PeerStore | None = None,
        origin_cluster=None,  # origin.client.ClusterClient (optional)
        announce_interval_seconds: float = 3.0,
        handout_policy=default_priority,
        handout_limit: int = 50,
        metainfo_cache_ttl: float = 60.0,
        fleet_addrs: list[str] | None = None,
        self_addr: str = "",
        shared_store: bool = False,
        forward_timeout_seconds: float = 2.0,
    ):
        self.peers = peer_store or InMemoryPeerStore()
        self.origin_cluster = origin_cluster
        self.interval = announce_interval_seconds
        self.policy = handout_policy
        self.handout_limit = handout_limit
        self._metainfo_cache: TTLCache = TTLCache(metainfo_cache_ttl)
        # Recipes are as immutable as metainfo (CAS: derived from the
        # blob's bytes), so the same TTL cache applies; /similar is NOT
        # cached -- its answer improves as blobs land.
        self._recipe_cache: TTLCache = TTLCache(metainfo_cache_ttl)
        # A handler failure swallowed as a bare 404 made a dying origin
        # cluster indistinguishable from a missing blob; meter + one
        # throttled WARN with request context instead.
        self._handler_errors = FailureMeter(
            "tracker_handler_errors_total",
            "Tracker handler failures previously swallowed as 404s",
            _log,
        )
        # -- fleet state (see module docstring) ---------------------------
        self.fleet_addrs = list(fleet_addrs or [])
        self.self_addr = self_addr
        # Trackers on a shared (Redis) store need no forwarding: every
        # tracker reads the same swarm records.
        self.shared_store = shared_store
        self._forward_http: HTTPClient | None = None
        self._forward_timeout = forward_timeout_seconds
        # The owner's availability, as THIS tracker sees it: forwarding
        # to a dead owner is wasted sockets, so forward failures trip a
        # local breaker and forwarding resumes via its half-open probe.
        self._forward_health = PassiveFilter(name="tracker-fleet-forward")
        # One forward per (info_hash, peer) per announce interval: the
        # owner re-learns a peer at the peer's own announce cadence, not
        # N-trackers times that.
        self._forward_throttle = TTLCache(
            max(announce_interval_seconds, 1.0), max_entries=8192
        )
        self._forward_tasks: set[asyncio.Task] = set()
        self._forwards = REGISTRY.counter(
            "tracker_announce_forwards_total",
            "Announces a non-owner tracker forwarded toward the shard"
            " owner, by outcome",
        )
        # Drain bookkeeping (LameduckMixin): announces/proxy reads that
        # must finish before the drain quiesces.
        self._inflight = 0

    def set_fleet(self, fleet_addrs: list[str], self_addr: str = "") -> None:
        """Swap fleet membership live (SIGHUP): ownership re-shards on
        the next announce; stale forward-breaker verdicts for departed
        trackers are pruned."""
        self.fleet_addrs = list(fleet_addrs)
        if self_addr:
            self.self_addr = self_addr
        self._forward_health.prune(self.fleet_addrs)

    @property
    def inflight_work(self) -> int:
        # debug_inflight: /debug/slo + /debug/ scrapes (`kraken-tpu
        # status`, canary tooling) gate the drain quiesce exactly like
        # the /recipe proxy reads below (the round-12 lesson).
        return self._inflight + self.debug_inflight

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/announce", self._announce)
        app.router.add_get("/namespace/{ns}/blobs/{d}/metainfo", self._metainfo)
        app.router.add_get("/namespace/{ns}/blobs/{d}/recipe", self._recipe)
        app.router.add_get("/namespace/{ns}/blobs/{d}/similar", self._similar)
        app.router.add_get("/health", self._health)
        self.add_lameduck_routes(app.router)
        self.bind_app(app)
        return app

    async def close(self) -> None:
        """Release fleet resources (forward tasks + client) and the peer
        store."""
        for t in list(self._forward_tasks):
            t.cancel()
        if self._forward_tasks:
            await asyncio.gather(*self._forward_tasks, return_exceptions=True)
        if self._forward_http is not None:
            await self._forward_http.close()
            self._forward_http = None
        await self.peers.close()

    async def _announce(self, req: web.Request) -> web.Response:
        if self.lameduck:
            # Draining: the 503 makes fleet clients fail over to the
            # next ring tracker NOW -- the rolling-restart contract.
            raise self.drain_unavailable()
        self._inflight += 1
        try:
            return await self._announce_inner(req)
        finally:
            self._inflight -= 1

    async def _announce_inner(self, req: web.Request) -> web.Response:
        # Failpoint tracker.announce.error: a flapping tracker -- clients
        # must meter the failure (announce_failures_total) and recover on
        # a later interval, not wedge or crash.
        if failpoints.fire("tracker.announce.error"):
            raise web.HTTPServiceUnavailable(
                text="failpoint tracker.announce.error"
            )
        try:
            doc = await req.json()
            info_hash = doc["info_hash"]
            if not isinstance(info_hash, str):
                # Opaque but must be a string: swarm keys are typed
                # (and e.g. a list is unhashable only at store time).
                raise ValueError("info_hash must be a string")
            peer = PeerInfo.from_dict(doc["peer"])
        except (json.JSONDecodeError, KeyError, ValueError,
                TypeError, AttributeError) as e:
            # TypeError/AttributeError: right keys, wrong shapes (a list
            # where an object belongs) -- still a malformed announce, not
            # a server error.
            raise web.HTTPBadRequest(text=f"malformed announce: {e}")
        # Record BEFORE reading: the store calls suspend the handler, so a
        # flash crowd of first announces handled read-first would all
        # snapshot the swarm before any write landed and every one would
        # get an empty handout. Writing first makes concurrent announcers
        # visible to each other; the announcer itself is filtered out of
        # its own handout below (hence the +1 overfetch).
        await self.peers.update(info_hash, peer)
        # Fleet mode: ALWAYS accepted locally (a handout must never
        # error because the shard owner died); additionally forwarded
        # toward a live owner so a membership-change straggler's
        # announce reaches the store most clients read from.
        if not req.headers.get(_FORWARDED_HEADER):
            self._maybe_forward(info_hash, doc)
        candidates = await self.peers.get_peers(
            info_hash, limit=self.handout_limit + 1
        )
        others = [
            p for p in candidates if p.peer_id != peer.peer_id
        ][: self.handout_limit]
        # Failpoint tracker.announce.empty: a 200 with an empty handout
        # (fresh tracker after restart, peer-store flush) -- leechers
        # must simply re-announce rather than treat it as terminal.
        if failpoints.fire("tracker.announce.empty"):
            others = []
        ordered = self.policy(others)
        ordered = self._shed_unhealthy_origins(ordered)
        return web.json_response(
            {
                "peers": [p.to_dict() for p in ordered],
                "interval": self.interval,
            }
        )

    def _shed_unhealthy_origins(
        self, peers: list[PeerInfo]
    ) -> list[PeerInfo]:
        """Breaker-aware handout: origin peers whose HOST the tracker's
        own origin-cluster breaker holds unhealthy (open, half-open, or
        browned out) move to the back of the handout, so leechers dial
        them only when everyone healthier is exhausted. Matching is by
        IP -- the breaker keys http addrs, announces carry p2p addrs --
        and only origin peers are shed: the breaker knows nothing about
        agent hosts."""
        health = getattr(self.origin_cluster, "health", None)
        if health is None or not hasattr(health, "unhealthy_hosts"):
            return peers

        def host_ip(h: str) -> str:
            h = h.split("://", 1)[-1]
            return h.rsplit(":", 1)[0]

        bad_ips = {host_ip(h) for h in health.unhealthy_hosts()}
        if not bad_ips:
            return peers
        return sorted(  # stable: policy order preserved within each half
            peers, key=lambda p: p.origin and p.ip in bad_ips
        )

    # -- fleet forwarding --------------------------------------------------

    def owns(self, info_hash: str) -> bool:
        """Shard ownership by the SAME rendezvous ranking the fleet
        client shards with; a tracker outside (or without) a fleet owns
        everything."""
        if not self.fleet_addrs or not self.self_addr:
            return True
        return rendezvous_hash(
            info_hash, self.fleet_addrs, k=1
        )[0] == self.self_addr

    def _maybe_forward(self, info_hash: str, doc: dict) -> None:
        """Best-effort re-announce toward the shard owner. Fire-and-
        forget: the announcer already has its answer from the local
        store; losing a forward costs one announce interval of owner-
        store freshness, never correctness. Skipped entirely on a shared
        store, when we ARE the owner, when the owner's forward breaker
        is open, or inside the per-peer throttle window."""
        if self.shared_store or not self.fleet_addrs or not self.self_addr:
            return
        owner = rendezvous_hash(info_hash, self.fleet_addrs, k=1)[0]
        if owner == self.self_addr:
            return
        peer_id = str(doc.get("peer", {}).get("peer_id", ""))
        throttle_key = (owner, info_hash, peer_id)
        if self._forward_throttle.get(throttle_key) is not None:
            self._forwards.inc(result="throttled")
            return
        if not self._forward_health.healthy(owner):
            # The owner is down as far as this tracker can tell -- the
            # announcer's own failover already landed the record here.
            self._forwards.inc(result="skipped_unhealthy")
            return
        self._forward_throttle.put(throttle_key, True)
        t = asyncio.create_task(self._forward(owner, doc))
        self._forward_tasks.add(t)
        t.add_done_callback(self._forward_tasks.discard)

    async def _forward(self, owner: str, doc: dict) -> None:
        if self._forward_http is None:
            self._forward_http = HTTPClient(
                timeout_seconds=self._forward_timeout, retries=0
            )
        try:
            await self._forward_http.post(
                f"{base_url(owner)}/announce",
                data=json.dumps(doc),
                headers={_FORWARDED_HEADER: "1"},
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            self._forward_health.failed(owner)
            self._forwards.inc(result="error")
        else:
            self._forward_health.succeeded(owner)
            self._forwards.inc(result="ok")

    # -- metainfo / delta proxies ------------------------------------------

    async def _metainfo(self, req: web.Request) -> web.Response:
        if self.lameduck:
            raise self.drain_unavailable()
        self._inflight += 1
        try:
            return await self._metainfo_inner(req)
        finally:
            self._inflight -= 1

    async def _metainfo_inner(self, req: web.Request) -> web.Response:
        ns, d = self._parse_digest(req)
        cached = self._metainfo_cache.get(d.hex)
        if cached is None:
            if self.origin_cluster is None:
                raise web.HTTPNotFound(text="no origin cluster configured")
            try:
                metainfo = await self.origin_cluster.get_metainfo(ns, d)
            except Exception as e:
                # Still a 404 to the caller (agents retry through their
                # announce loop), but never a SILENT one: an origin
                # cluster that is down looks exactly like a missing blob
                # otherwise. Metered + one throttled WARN with context.
                self._handler_errors.record(
                    f"metainfo fetch {d.hex[:12]} ns={ns} "
                    f"peer={req.remote}", e,
                )
                raise web.HTTPNotFound(text="metainfo unavailable")
            cached = metainfo.serialize()
            self._metainfo_cache.put(d.hex, cached)
        return web.Response(body=cached)

    def _parse_digest(self, req: web.Request) -> tuple[str, Digest]:
        ns = urllib.parse.unquote(req.match_info["ns"])
        try:
            return ns, Digest.from_str(req.match_info["d"])
        except DigestError:
            raise web.HTTPBadRequest(text="malformed digest")

    async def _recipe(self, req: web.Request) -> web.Response:
        """Delta-plane proxy: the blob's chunk recipe from the origin
        cluster, with the serving origin's addr stamped on the response
        (``X-Kraken-Origin``) so agents can aim range fetches at it. A
        clean origin 404 (delta disabled, blob gone) is the expected
        steady state while delta is rolled out -- it is NOT a handler
        error."""
        if self.lameduck:
            raise self.drain_unavailable()
        self._inflight += 1
        try:
            return await self._recipe_inner(req)
        finally:
            self._inflight -= 1

    async def _recipe_inner(self, req: web.Request) -> web.Response:
        ns, d = self._parse_digest(req)
        cached = self._recipe_cache.get(d.hex)
        if cached is None:
            if self.origin_cluster is None:
                raise web.HTTPNotFound(text="no origin cluster configured")
            try:
                raw, addr = await self.origin_cluster.get_recipe(ns, d)
            except Exception as e:
                if not is_not_found(e):
                    self._handler_errors.record(
                        f"recipe fetch {d.hex[:12]} ns={ns} "
                        f"peer={req.remote}", e,
                    )
                raise web.HTTPNotFound(text="recipe unavailable")
            cached = (raw, addr)
            self._recipe_cache.put(d.hex, cached)
        raw, addr = cached
        return web.Response(
            body=raw,
            content_type="application/json",
            headers={"X-Kraken-Origin": addr},
        )

    async def _similar(self, req: web.Request) -> web.Response:
        """Delta-plane proxy: near-duplicate candidates from the origin
        cluster's dedup index (uncached: the answer improves as blobs
        land)."""
        if self.lameduck:
            raise self.drain_unavailable()
        self._inflight += 1
        try:
            return await self._similar_inner(req)
        finally:
            self._inflight -= 1

    async def _similar_inner(self, req: web.Request) -> web.Response:
        ns, d = self._parse_digest(req)
        if self.origin_cluster is None:
            raise web.HTTPNotFound(text="no origin cluster configured")
        try:
            k = int(req.query.get("k", "10"))
        except ValueError:
            raise web.HTTPBadRequest(text="malformed k")
        if k <= 0:
            # Reject here: forwarded, the origin's 400 would both read
            # as 404 to the caller and pollute _handler_errors -- the
            # meter that distinguishes a dying origin cluster from a
            # missing blob.
            raise web.HTTPBadRequest(text="k must be > 0")
        try:
            hits = await self.origin_cluster.similar(ns, d, k=k)
        except Exception as e:
            if not is_not_found(e):
                self._handler_errors.record(
                    f"similar fetch {d.hex[:12]} ns={ns} "
                    f"peer={req.remote}", e,
                )
            raise web.HTTPNotFound(text="similar unavailable")
        return web.json_response({"similar": hits})

    async def _health(self, req: web.Request) -> web.Response:
        if self.lameduck:
            # Rolling restart: the deploy system (and any LB) observes
            # the flip, waits its grace period, then SIGTERMs -- the
            # same contract agents and origins honor.
            raise self.drain_unavailable()
        return web.Response(text="ok")

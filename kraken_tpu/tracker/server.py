"""Tracker HTTP API: announce + metainfo proxy.

Mirrors uber/kraken ``tracker/trackerserver`` (announce endpoint: peer <->
peer-list exchange with an announce interval; metainfo endpoint proxying
the origin cluster with a TTL cache) -- upstream path, unverified;
SURVEY.md SS2.4/SS3.4.

Endpoints:

    POST /announce                 body: announce record   -> {peers, interval}
    GET  /namespace/{ns}/blobs/{d}/metainfo               -> metainfo doc
    GET  /namespace/{ns}/blobs/{d}/recipe                 -> chunk recipe
                                                             (X-Kraken-Origin:
                                                             serving origin)
    GET  /namespace/{ns}/blobs/{d}/similar                -> near-dup list
    GET  /health

Agents know only the tracker, so the delta-transfer control plane
(recipes + /similar) proxies through it exactly like metainfo; the
``X-Kraken-Origin`` header names the origin that served the recipe so
agents can aim byte-range fetches at a replica that actually holds the
blob.
"""

from __future__ import annotations

import json
import logging
import urllib.parse

from aiohttp import web

from kraken_tpu.core.digest import Digest, DigestError
from kraken_tpu.core.peer import PeerInfo
from kraken_tpu.tracker.peerhandout import default_priority
from kraken_tpu.tracker.peerstore import InMemoryPeerStore, PeerStore
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.dedup import TTLCache
from kraken_tpu.utils.httputil import is_not_found
from kraken_tpu.utils.metrics import FailureMeter

_log = logging.getLogger("kraken.tracker")


class TrackerServer:
    def __init__(
        self,
        peer_store: PeerStore | None = None,
        origin_cluster=None,  # origin.client.ClusterClient (optional)
        announce_interval_seconds: float = 3.0,
        handout_policy=default_priority,
        handout_limit: int = 50,
        metainfo_cache_ttl: float = 60.0,
    ):
        self.peers = peer_store or InMemoryPeerStore()
        self.origin_cluster = origin_cluster
        self.interval = announce_interval_seconds
        self.policy = handout_policy
        self.handout_limit = handout_limit
        self._metainfo_cache: TTLCache = TTLCache(metainfo_cache_ttl)
        # Recipes are as immutable as metainfo (CAS: derived from the
        # blob's bytes), so the same TTL cache applies; /similar is NOT
        # cached -- its answer improves as blobs land.
        self._recipe_cache: TTLCache = TTLCache(metainfo_cache_ttl)
        # A handler failure swallowed as a bare 404 made a dying origin
        # cluster indistinguishable from a missing blob; meter + one
        # throttled WARN with request context instead.
        self._handler_errors = FailureMeter(
            "tracker_handler_errors_total",
            "Tracker handler failures previously swallowed as 404s",
            _log,
        )

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/announce", self._announce)
        app.router.add_get("/namespace/{ns}/blobs/{d}/metainfo", self._metainfo)
        app.router.add_get("/namespace/{ns}/blobs/{d}/recipe", self._recipe)
        app.router.add_get("/namespace/{ns}/blobs/{d}/similar", self._similar)
        app.router.add_get("/health", self._health)
        return app

    async def _announce(self, req: web.Request) -> web.Response:
        # Failpoint tracker.announce.error: a flapping tracker -- clients
        # must meter the failure (announce_failures_total) and recover on
        # a later interval, not wedge or crash.
        if failpoints.fire("tracker.announce.error"):
            raise web.HTTPServiceUnavailable(
                text="failpoint tracker.announce.error"
            )
        try:
            doc = await req.json()
            info_hash = doc["info_hash"]
            if not isinstance(info_hash, str):
                # Opaque but must be a string: swarm keys are typed
                # (and e.g. a list is unhashable only at store time).
                raise ValueError("info_hash must be a string")
            peer = PeerInfo.from_dict(doc["peer"])
        except (json.JSONDecodeError, KeyError, ValueError,
                TypeError, AttributeError) as e:
            # TypeError/AttributeError: right keys, wrong shapes (a list
            # where an object belongs) -- still a malformed announce, not
            # a server error.
            raise web.HTTPBadRequest(text=f"malformed announce: {e}")
        # Record BEFORE reading: the store calls suspend the handler, so a
        # flash crowd of first announces handled read-first would all
        # snapshot the swarm before any write landed and every one would
        # get an empty handout. Writing first makes concurrent announcers
        # visible to each other; the announcer itself is filtered out of
        # its own handout below (hence the +1 overfetch).
        await self.peers.update(info_hash, peer)
        candidates = await self.peers.get_peers(
            info_hash, limit=self.handout_limit + 1
        )
        others = [
            p for p in candidates if p.peer_id != peer.peer_id
        ][: self.handout_limit]
        # Failpoint tracker.announce.empty: a 200 with an empty handout
        # (fresh tracker after restart, peer-store flush) -- leechers
        # must simply re-announce rather than treat it as terminal.
        if failpoints.fire("tracker.announce.empty"):
            others = []
        ordered = self.policy(others)
        ordered = self._shed_unhealthy_origins(ordered)
        return web.json_response(
            {
                "peers": [p.to_dict() for p in ordered],
                "interval": self.interval,
            }
        )

    def _shed_unhealthy_origins(
        self, peers: list[PeerInfo]
    ) -> list[PeerInfo]:
        """Breaker-aware handout: origin peers whose HOST the tracker's
        own origin-cluster breaker holds unhealthy (open, half-open, or
        browned out) move to the back of the handout, so leechers dial
        them only when everyone healthier is exhausted. Matching is by
        IP -- the breaker keys http addrs, announces carry p2p addrs --
        and only origin peers are shed: the breaker knows nothing about
        agent hosts."""
        health = getattr(self.origin_cluster, "health", None)
        if health is None or not hasattr(health, "unhealthy_hosts"):
            return peers

        def host_ip(h: str) -> str:
            h = h.split("://", 1)[-1]
            return h.rsplit(":", 1)[0]

        bad_ips = {host_ip(h) for h in health.unhealthy_hosts()}
        if not bad_ips:
            return peers
        return sorted(  # stable: policy order preserved within each half
            peers, key=lambda p: p.origin and p.ip in bad_ips
        )

    async def _metainfo(self, req: web.Request) -> web.Response:
        ns, d = self._parse_digest(req)
        cached = self._metainfo_cache.get(d.hex)
        if cached is None:
            if self.origin_cluster is None:
                raise web.HTTPNotFound(text="no origin cluster configured")
            try:
                metainfo = await self.origin_cluster.get_metainfo(ns, d)
            except Exception as e:
                # Still a 404 to the caller (agents retry through their
                # announce loop), but never a SILENT one: an origin
                # cluster that is down looks exactly like a missing blob
                # otherwise. Metered + one throttled WARN with context.
                self._handler_errors.record(
                    f"metainfo fetch {d.hex[:12]} ns={ns} "
                    f"peer={req.remote}", e,
                )
                raise web.HTTPNotFound(text="metainfo unavailable")
            cached = metainfo.serialize()
            self._metainfo_cache.put(d.hex, cached)
        return web.Response(body=cached)

    def _parse_digest(self, req: web.Request) -> tuple[str, Digest]:
        ns = urllib.parse.unquote(req.match_info["ns"])
        try:
            return ns, Digest.from_str(req.match_info["d"])
        except DigestError:
            raise web.HTTPBadRequest(text="malformed digest")

    async def _recipe(self, req: web.Request) -> web.Response:
        """Delta-plane proxy: the blob's chunk recipe from the origin
        cluster, with the serving origin's addr stamped on the response
        (``X-Kraken-Origin``) so agents can aim range fetches at it. A
        clean origin 404 (delta disabled, blob gone) is the expected
        steady state while delta is rolled out -- it is NOT a handler
        error."""
        ns, d = self._parse_digest(req)
        cached = self._recipe_cache.get(d.hex)
        if cached is None:
            if self.origin_cluster is None:
                raise web.HTTPNotFound(text="no origin cluster configured")
            try:
                raw, addr = await self.origin_cluster.get_recipe(ns, d)
            except Exception as e:
                if not is_not_found(e):
                    self._handler_errors.record(
                        f"recipe fetch {d.hex[:12]} ns={ns} "
                        f"peer={req.remote}", e,
                    )
                raise web.HTTPNotFound(text="recipe unavailable")
            cached = (raw, addr)
            self._recipe_cache.put(d.hex, cached)
        raw, addr = cached
        return web.Response(
            body=raw,
            content_type="application/json",
            headers={"X-Kraken-Origin": addr},
        )

    async def _similar(self, req: web.Request) -> web.Response:
        """Delta-plane proxy: near-duplicate candidates from the origin
        cluster's dedup index (uncached: the answer improves as blobs
        land)."""
        ns, d = self._parse_digest(req)
        if self.origin_cluster is None:
            raise web.HTTPNotFound(text="no origin cluster configured")
        try:
            k = int(req.query.get("k", "10"))
        except ValueError:
            raise web.HTTPBadRequest(text="malformed k")
        if k <= 0:
            # Reject here: forwarded, the origin's 400 would both read
            # as 404 to the caller and pollute _handler_errors -- the
            # meter that distinguishes a dying origin cluster from a
            # missing blob.
            raise web.HTTPBadRequest(text="k must be > 0")
        try:
            hits = await self.origin_cluster.similar(ns, d, k=k)
        except Exception as e:
            if not is_not_found(e):
                self._handler_errors.record(
                    f"similar fetch {d.hex[:12]} ns={ns} "
                    f"peer={req.remote}", e,
                )
            raise web.HTTPNotFound(text="similar unavailable")
        return web.json_response({"similar": hits})

    async def _health(self, req: web.Request) -> web.Response:
        return web.Response(text="ok")

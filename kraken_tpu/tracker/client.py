"""Agent-side tracker clients: announce + metainfo fetch, single host or
sharded fleet.

Mirrors uber/kraken ``tracker/announceclient`` + ``tracker/metainfoclient``
-- upstream paths, unverified; SURVEY.md SS2.4. Both classes implement the
scheduler's ``AnnounceClient`` / ``MetaInfoClient`` protocols.

- :class:`TrackerClient` -- one tracker address (the pre-fleet shape;
  still what tests and single-tracker rigs construct directly).
- :class:`TrackerFleetClient` -- N tracker addresses. Each request
  shards by its swarm key (info hash for announces, blob digest for
  metainfo/recipes) over the SAME rendezvous hashring the origin ring
  uses (placement/hashring.py), so in a healthy fleet every tracker owns
  a stable slice of the announce load. On failure the request fails over
  along the ring through the shared degradation machinery
  (placement/replicawalk.py): per-tracker-host circuit breakers, probe
  admission, deadline-budgeted walks, and hedged metainfo/recipe reads.
  Drop-in for the scheduler -- announce loops, delta planning, and
  origin seed-announces inherit failover untouched.

Every announce runs under an explicit total budget
(``announce_timeout_seconds`` -> utils/deadline.Deadline): before round 8
the announce POST had NO timeout at all, so one hung tracker socket
stalled the scheduler's announce loop forever -- the announce queue kept
popping, but the in-flight task never returned. Exhaustion is counted on
``announce_timeouts_total`` and raises, which the scheduler's announce
loop already meters and backs off (decorrelated jitter, round 12).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import ChunkRecipe, InfoHash, MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from urllib.parse import quote

from kraken_tpu.placement.healthcheck import PassiveFilter
from kraken_tpu.placement.hrw import rendezvous_hash
from kraken_tpu.placement.replicawalk import walk_replicas
from kraken_tpu.utils import failpoints, trace
from kraken_tpu.utils.deadline import Deadline, DeadlineExceeded
from kraken_tpu.utils.dedup import TTLCache
from kraken_tpu.utils.httputil import HTTPClient, base_url
from kraken_tpu.utils.metrics import REGISTRY

_log = logging.getLogger("kraken.tracker.client")

# Unique per-instance breaker names: /debug/healthcheck keys its
# snapshot by filter name, and an in-process herd (or a test session)
# holds several fleet clients at once -- a shared name would let one
# client's view shadow another's on the operator surface.
_fleet_seq = itertools.count()


def _count_announce_timeout() -> None:
    REGISTRY.counter(
        "announce_timeouts_total",
        "Tracker announces abandoned at their total time budget",
    ).inc()


class _RecipeCache:
    """Agent-side TTL cache for the delta-plane control reads
    (``get_recipe`` / ``similar``): a tracker failover must never
    re-fetch a recipe the agent just had (recipes are CAS-immutable;
    /similar staleness is bounded by the TTL). Hits and misses count on
    ``tracker_recipe_cache_total{op,result}``. TTL 0 disables."""

    def __init__(self, ttl_seconds: float, max_entries: int = 1024):
        self.ttl = ttl_seconds
        self._cache: TTLCache | None = (
            TTLCache(ttl_seconds, max_entries=max_entries)
            if ttl_seconds > 0 else None
        )
        self._counter = REGISTRY.counter(
            "tracker_recipe_cache_total",
            "Agent-side delta-plane cache outcomes (recipe + /similar"
            " lookups), by op and hit/miss",
        )

    def get(self, op: str, key):
        if self._cache is None:
            return None
        hit = self._cache.get(key)
        self._counter.inc(op=op, result="hit" if hit is not None else "miss")
        return hit

    def put(self, op: str, key, value) -> None:
        if self._cache is not None:
            self._cache.put(key, value)


class TrackerClient:
    """Both announce and metainfo against one tracker address."""

    def __init__(
        self,
        addr: str,
        peer_id: PeerID,
        ip: str,
        port: int,
        is_origin: bool = False,
        http: HTTPClient | None = None,
        announce_timeout_seconds: float = 5.0,
        recipe_cache_ttl_seconds: float = 0.0,
    ):
        self.addr = addr
        self.peer_id = peer_id
        self.ip = ip
        self.port = port
        self.is_origin = is_origin
        self._http = http or HTTPClient()
        # Per-announce TOTAL budget (retries included); the per-attempt
        # timeout becomes min(http timeout, remaining budget). 0 = the
        # legacy unbounded announce (discouraged; kept for tests).
        self.announce_timeout = announce_timeout_seconds
        # Delta-plane read cache (agents pass a TTL; default off so
        # direct/administrative constructions stay uncached).
        self._recipes = _RecipeCache(recipe_cache_ttl_seconds)

    async def announce(
        self, d: Digest, h: InfoHash, namespace: str, complete: bool,
        deadline: Deadline | None = None,
    ) -> tuple[list[PeerInfo], float]:
        me = PeerInfo(
            peer_id=self.peer_id,
            ip=self.ip,
            port=self.port,
            origin=self.is_origin,
            complete=complete,
        )
        # Failpoint tracker.blackout: this tracker is DARK (bad deploy,
        # dead shared backend) -- a typed connectivity failure, exactly
        # what a refused socket raises, so breakers trip and the fleet
        # outage latch engages through the production path.
        if failpoints.fire("tracker.blackout"):
            raise ConnectionError("failpoint tracker.blackout")
        # An externally-supplied deadline (the fleet client's walk
        # budget) is owned by the caller: IT counts the exhaustion, this
        # hop only propagates it.
        own_budget = deadline is None
        if own_budget and self.announce_timeout:
            deadline = Deadline(self.announce_timeout, component="announce")
        try:
            # The announce span is what /debug/trace shows for the hop;
            # the HTTP client span inside injects the traceparent header
            # so the tracker's server span joins the same trace.
            # `d` is optional here (announce by bare info hash): the
            # span must not be the first thing that dereferences it.
            with trace.span(
                "tracker.announce",
                digest=d.hex[:12] if d is not None else "",
                complete=complete,
            ):
                body = await self._http.post(
                    f"{base_url(self.addr)}/announce",
                    data=json.dumps(
                        {"info_hash": h.hex, "peer": me.to_dict()}
                    ),
                    deadline=deadline,
                )
        except DeadlineExceeded:
            if own_budget:
                _count_announce_timeout()
            raise
        doc = json.loads(body)
        return [PeerInfo.from_dict(p) for p in doc["peers"]], float(doc["interval"])

    async def get(
        self, namespace: str, d: Digest, deadline: Deadline | None = None
    ) -> MetaInfo:
        if failpoints.fire("tracker.blackout"):
            raise ConnectionError("failpoint tracker.blackout")
        with trace.span("tracker.get_metainfo", digest=d.hex[:12]):
            raw = await self._http.get(
                f"{base_url(self.addr)}/namespace/"
                f"{quote(namespace, safe='')}/blobs/{d.hex}/metainfo",
                deadline=deadline,
            )
        return MetaInfo.deserialize(raw)

    async def get_recipe(
        self, namespace: str, d: Digest, deadline: Deadline | None = None
    ) -> tuple[ChunkRecipe, str]:
        """The blob's chunk recipe (delta-transfer plane), proxied from
        the origin cluster, plus the serving origin's addr (the
        ``X-Kraken-Origin`` response header; '' when absent) -- where the
        planner aims its byte-range fetches. Raises HTTPError on 404
        (delta disabled or blob unknown): misses are an expected state
        the planner degrades through, so no retries (and no negative
        caching -- the blob may land any moment)."""
        cached = self._recipes.get("recipe", (namespace, d.hex))
        if cached is not None:
            return cached
        with trace.span("tracker.get_recipe", digest=d.hex[:12]):
            _status, headers, body = await self._http.request_full(
                "GET",
                f"{base_url(self.addr)}/namespace/"
                f"{quote(namespace, safe='')}/blobs/{d.hex}/recipe",
                retry_5xx=False,
                deadline=deadline,
            )
        out = ChunkRecipe.deserialize(body), headers.get(
            "X-Kraken-Origin", ""
        )
        self._recipes.put("recipe", (namespace, d.hex), out)
        return out

    async def similar(
        self, namespace: str, d: Digest, deadline: Deadline | None = None
    ) -> list[dict]:
        """Near-duplicate candidates for ``d`` (delta base selection):
        [{"digest": hex, "score": estimated-Jaccard}], best first."""
        cached = self._recipes.get("similar", ("~", namespace, d.hex))
        if cached is not None:
            return cached
        with trace.span("tracker.get_similar", digest=d.hex[:12]):
            raw = await self._http.get(
                f"{base_url(self.addr)}/namespace/"
                f"{quote(namespace, safe='')}/blobs/{d.hex}/similar",
                retry_5xx=False,
                deadline=deadline,
            )
        out = json.loads(raw)["similar"]
        self._recipes.put("similar", ("~", namespace, d.hex), out)
        return out

    async def close(self) -> None:
        await self._http.close()


class TrackerFleetClient:
    """N tracker addrs behind the scheduler's client protocols.

    Sharding: each request ranks the fleet with the same rendezvous hash
    the origin hashring uses (placement/hrw.py), keyed by the swarm's
    info hash (announces) or the blob digest (metainfo/recipe/similar).
    The top-ranked tracker is the shard OWNER; the rest of the ranking
    is the failover order. The per-host breaker
    (placement/healthcheck.PassiveFilter) sheds open/browned-out
    trackers toward the back of that order, so a dead tracker costs its
    shard at most `fail_threshold` slow announces before every client
    routes around it -- and the half-open probe re-admits it after the
    cooldown without a thundering herd.

    Announces walk serially (failover, no hedging: doubling announce
    write load fleet-wide buys nothing). Metainfo/recipe/similar reads
    HEDGE exactly like origin cluster reads: after ``hedge_delay``
    without an answer the next ranked tracker joins the race.

    ``set_addrs`` swaps the fleet live (SIGHUP reload of the tracker
    list): ownership re-shards by rendezvous hashing, so adding or
    removing one tracker moves only ~1/N of the swarms.
    """

    def __init__(
        self,
        addrs: list[str],
        peer_id: PeerID,
        ip: str,
        port: int,
        is_origin: bool = False,
        http: HTTPClient | None = None,
        announce_timeout_seconds: float = 5.0,
        request_deadline_seconds: float = 60.0,
        hedge_delay_seconds: float | None = 0.3,
        recipe_cache_ttl_seconds: float = 0.0,
        health: PassiveFilter | None = None,
    ):
        if not addrs:
            raise ValueError("tracker fleet needs at least one addr")
        self.peer_id = peer_id
        self.ip = ip
        self._port = port
        self.is_origin = is_origin
        self._http = http or HTTPClient()
        self.announce_timeout = announce_timeout_seconds
        self.request_deadline = request_deadline_seconds
        self.hedge_delay = hedge_delay_seconds or None
        self.health = health or PassiveFilter(
            name=f"tracker-fleet-{next(_fleet_seq)}"
        )
        self._addrs: list[str] = []
        # addr -> TrackerClient; sub-clients share ONE HTTPClient (and
        # are never individually closed -- close() closes the session).
        self._clients: dict[str, TrackerClient] = {}
        self._failovers = REGISTRY.counter(
            "tracker_fleet_failovers_total",
            "Requests served by a tracker other than their shard owner",
        )
        # Total-outage latch: every breaker open at once means the whole
        # tracker plane is down, and walking the full failover order at
        # full budget per request is pure queue-building. While latched,
        # walks with no probe-eligible tracker fail fast (no HTTP); the
        # latch clears only on a SUCCESSFUL walk (hysteresis -- one
        # breaker entering half-open is a probe opportunity, not
        # recovery). Registered eagerly so the gauge exists at 0 before
        # the first outage.
        self.outage = False
        self._outage_accrue_t = 0.0
        self._outage_gauge = REGISTRY.gauge(
            "tracker_outage",
            "1 while every tracker in the fleet is breaker-open (total "
            "tracker outage), else 0",
        )
        self._outage_gauge.set(0)
        self._outages_total = REGISTRY.counter(
            "tracker_outages_total",
            "Transitions into total tracker outage (all breakers open)",
        )
        self._outage_seconds = REGISTRY.counter(
            "tracker_outage_seconds_total",
            "Seconds spent with the tracker outage latch engaged",
        )
        self._recipes = _RecipeCache(recipe_cache_ttl_seconds)
        self.set_addrs(addrs)

    # -- membership --------------------------------------------------------

    @property
    def addrs(self) -> list[str]:
        return list(self._addrs)

    @property
    def addr(self) -> str:
        """Single-addr compatibility surface (logs, tests): the fleet's
        membership as one comma-joined string."""
        return ",".join(self._addrs)

    def set_addrs(self, addrs: list[str]) -> None:
        """Swap the fleet membership live (SIGHUP). Dropped trackers
        lose their clients and breaker verdicts (a departed addr's stale
        verdict must not greet a reused address); survivors keep
        theirs."""
        if not addrs:
            raise ValueError("tracker fleet needs at least one addr")
        self._addrs = list(dict.fromkeys(addrs))  # de-dup, keep order
        for gone in set(self._clients) - set(self._addrs):
            del self._clients[gone]
        self.health.prune(self._addrs)

    @property
    def port(self) -> int:
        return self._port

    @port.setter
    def port(self, value: int) -> None:
        # Assembly learns the p2p port only after the scheduler binds;
        # the setter fans it out so every sub-client announces it.
        self._port = value
        for c in self._clients.values():
            c.port = value

    def _client(self, addr: str) -> TrackerClient:
        c = self._clients.get(addr)
        if c is None:
            c = self._clients[addr] = TrackerClient(
                addr, self.peer_id, self.ip, self._port,
                is_origin=self.is_origin, http=self._http,
                # The walk owns the budget; sub-clients never start one.
                announce_timeout_seconds=0.0,
            )
        return c

    def clients_for(self, key_hex: str) -> list[TrackerClient]:
        """The full fleet ranked for ``key_hex``: rendezvous order
        (owner first), breaker-unhealthy trackers shed toward the back."""
        ranked = rendezvous_hash(key_hex, self._addrs, k=len(self._addrs))
        return [self._client(a) for a in self.health.order(ranked)]

    def owner_of(self, key_hex: str) -> str:
        """The shard owner for ``key_hex`` (breaker-blind placement --
        where the request goes when the whole fleet is healthy)."""
        return rendezvous_hash(key_hex, self._addrs, k=1)[0]

    def _outage_check(self) -> None:
        """Walk-entry gate for the total-outage latch.

        ``PassiveFilter.healthy`` is False only for OPEN-AND-COOLING
        breakers -- past the cooldown it reads True again (the half-open
        probe invitation). So "every addr unhealthy" simultaneously
        means "total outage" and "nothing is probe-eligible right now":
        latch and fail fast with a typed error instead of burning the
        full walk budget on sockets we already know are dark. The
        moment any cooldown expires the addr reads healthy, this gate
        passes, and the walk itself becomes the probe. Clearing the
        latch is ``_walk``'s success path, never this gate (hysteresis).
        """
        now = time.monotonic()
        if self.outage:
            self._outage_seconds.inc(max(0.0, now - self._outage_accrue_t))
            self._outage_accrue_t = now
        if not all(not self.health.healthy(a, now) for a in self._addrs):
            return
        if not self.outage:
            self.outage = True
            self._outage_accrue_t = now
            self._outage_gauge.set(1)
            self._outages_total.inc()
            _log.error(
                "tracker fleet outage: all %d trackers breaker-open (%s)",
                len(self._addrs), ",".join(self._addrs),
            )
            from kraken_tpu.utils.trace import TRACER
            TRACER.trigger_dump(
                "tracker_outage",
                f"all {len(self._addrs)} trackers breaker-open",
            )
        raise ConnectionError(
            "tracker fleet outage: all trackers breaker-open"
        )

    async def _walk(self, key_hex: str, op, *, op_name: str,
                    deadline: Deadline, hedge: bool):
        """Shared walk wrapper: counts a failover whenever the serving
        tracker is not the shard owner (the operator's 'how much load is
        off-placement' signal).

        Serial walks additionally slice the budget PER ATTEMPT
        (total / fleet size): a BLACKHOLED tracker (partition, not a
        clean RST) must not eat the whole walk budget on attempt one --
        the slice's TimeoutError IS host evidence (unlike a spent
        walk-wide deadline, which deliberately is not), so the breaker
        counts it, the walk reaches a survivor inside the budget, and
        after ``fail_threshold`` announces the fleet routes around the
        corpse entirely. Hedged walks need no slice: the hedge timer
        already races past a hung primary."""
        self._outage_check()
        owner = self.owner_of(key_hex)
        served: list[str] = []
        per_attempt = (
            deadline.remaining() / len(self._addrs)
            if deadline is not None and not hedge and len(self._addrs) > 1
            else None
        )

        async def op2(c, dl):
            if per_attempt is not None:
                cap = per_attempt
                if dl is not None:
                    cap = min(cap, max(0.001, dl.remaining()))
                out = await asyncio.wait_for(op(c, dl), cap)
            else:
                out = await op(c, dl)
            served.append(c.addr)
            return out

        result = await walk_replicas(
            self.clients_for(key_hex), op2,
            key=key_hex[:12], health=self.health,
            hedge_delay=self.hedge_delay if hedge else None,
            deadline=deadline, op_name=op_name,
        )
        if served and served[0] != owner:
            self._failovers.inc(op=op_name)
        if self.outage:
            # A whole walk succeeded end to end: that is recovery, not a
            # half-open flicker -- unlatch.
            self.outage = False
            self._outage_seconds.inc(
                max(0.0, time.monotonic() - self._outage_accrue_t)
            )
            self._outage_gauge.set(0)
            _log.warning("tracker fleet recovered from total outage")
        return result

    # -- the client protocols ----------------------------------------------

    async def announce(
        self, d: Digest, h: InfoHash, namespace: str, complete: bool
    ) -> tuple[list[PeerInfo], float]:
        deadline = (
            Deadline(self.announce_timeout, component="announce")
            if self.announce_timeout else None
        )
        try:
            return await self._walk(
                h.hex,
                lambda c, dl: c.announce(d, h, namespace, complete,
                                         deadline=dl),
                op_name="announce", deadline=deadline, hedge=False,
            )
        except DeadlineExceeded:
            _count_announce_timeout()
            raise

    async def get(self, namespace: str, d: Digest) -> MetaInfo:
        return await self._walk(
            d.hex,
            lambda c, dl: c.get(namespace, d, deadline=dl),
            op_name="tracker_metainfo",
            deadline=Deadline(self.request_deadline,
                              component="tracker-fleet"),
            hedge=True,
        )

    async def get_recipe(
        self, namespace: str, d: Digest
    ) -> tuple[ChunkRecipe, str]:
        cached = self._recipes.get("recipe", (namespace, d.hex))
        if cached is not None:
            return cached
        out = await self._walk(
            d.hex,
            lambda c, dl: c.get_recipe(namespace, d, deadline=dl),
            op_name="tracker_recipe",
            deadline=Deadline(self.request_deadline,
                              component="tracker-fleet"),
            hedge=True,
        )
        self._recipes.put("recipe", (namespace, d.hex), out)
        return out

    async def similar(self, namespace: str, d: Digest) -> list[dict]:
        cached = self._recipes.get("similar", ("~", namespace, d.hex))
        if cached is not None:
            return cached
        out = await self._walk(
            d.hex,
            lambda c, dl: c.similar(namespace, d, deadline=dl),
            op_name="tracker_similar",
            deadline=Deadline(self.request_deadline,
                              component="tracker-fleet"),
            hedge=True,
        )
        self._recipes.put("similar", ("~", namespace, d.hex), out)
        return out

    async def close(self) -> None:
        await self._http.close()


def parse_tracker_addrs(spec: str | list[str]) -> list[str]:
    """One config shape for 'the tracker(s)': a comma-separated string
    (YAML/flag) or an explicit list. Empty entries drop out."""
    if isinstance(spec, str):
        spec = spec.split(",")
    return [a.strip() for a in spec if a and a.strip()]


def make_tracker_client(
    spec: str | list[str],
    peer_id: PeerID,
    ip: str,
    port: int,
    is_origin: bool = False,
    announce_timeout_seconds: float = 5.0,
    request_deadline_seconds: float = 60.0,
    hedge_delay_seconds: float | None = 0.3,
    recipe_cache_ttl_seconds: float = 0.0,
):
    """Assembly's one constructor for 'the tracker client': a fleet
    client for >= 2 addrs, the plain single-host client otherwise (0 or
    1 addr keeps the pre-fleet behavior bit-for-bit, including the
    legacy empty-addr construction some harnesses rely on)."""
    addrs = parse_tracker_addrs(spec)
    if len(addrs) >= 2:
        return TrackerFleetClient(
            addrs, peer_id, ip, port, is_origin=is_origin,
            announce_timeout_seconds=announce_timeout_seconds,
            request_deadline_seconds=request_deadline_seconds,
            hedge_delay_seconds=hedge_delay_seconds,
            recipe_cache_ttl_seconds=recipe_cache_ttl_seconds,
        )
    single = addrs[0] if addrs else (spec if isinstance(spec, str) else "")
    return TrackerClient(
        single, peer_id, ip, port, is_origin=is_origin,
        announce_timeout_seconds=announce_timeout_seconds,
        recipe_cache_ttl_seconds=recipe_cache_ttl_seconds,
    )

"""Agent-side tracker clients: announce + metainfo fetch.

Mirrors uber/kraken ``tracker/announceclient`` + ``tracker/metainfoclient``
-- upstream paths, unverified; SURVEY.md SS2.4. These implement the
scheduler's ``AnnounceClient`` / ``MetaInfoClient`` protocols.

Every announce runs under an explicit total budget
(``announce_timeout_seconds`` -> utils/deadline.Deadline): before round 8
the announce POST had NO timeout at all, so one hung tracker socket
stalled the scheduler's announce loop forever -- the announce queue kept
popping, but the in-flight task never returned. Exhaustion is counted on
``announce_timeouts_total`` and raises, which the scheduler's announce
loop already meters and retries next interval.
"""

from __future__ import annotations

import json

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import ChunkRecipe, InfoHash, MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from urllib.parse import quote

from kraken_tpu.utils import trace
from kraken_tpu.utils.deadline import Deadline, DeadlineExceeded
from kraken_tpu.utils.httputil import HTTPClient, base_url
from kraken_tpu.utils.metrics import REGISTRY


class TrackerClient:
    """Both announce and metainfo against one tracker address."""

    def __init__(
        self,
        addr: str,
        peer_id: PeerID,
        ip: str,
        port: int,
        is_origin: bool = False,
        http: HTTPClient | None = None,
        announce_timeout_seconds: float = 5.0,
    ):
        self.addr = addr
        self.peer_id = peer_id
        self.ip = ip
        self.port = port
        self.is_origin = is_origin
        self._http = http or HTTPClient()
        # Per-announce TOTAL budget (retries included); the per-attempt
        # timeout becomes min(http timeout, remaining budget). 0 = the
        # legacy unbounded announce (discouraged; kept for tests).
        self.announce_timeout = announce_timeout_seconds

    async def announce(
        self, d: Digest, h: InfoHash, namespace: str, complete: bool
    ) -> tuple[list[PeerInfo], float]:
        me = PeerInfo(
            peer_id=self.peer_id,
            ip=self.ip,
            port=self.port,
            origin=self.is_origin,
            complete=complete,
        )
        deadline = (
            Deadline(self.announce_timeout, component="announce")
            if self.announce_timeout
            else None
        )
        try:
            # The announce span is what /debug/trace shows for the hop;
            # the HTTP client span inside injects the traceparent header
            # so the tracker's server span joins the same trace.
            # `d` is optional here (announce by bare info hash): the
            # span must not be the first thing that dereferences it.
            with trace.span(
                "tracker.announce",
                digest=d.hex[:12] if d is not None else "",
                complete=complete,
            ):
                body = await self._http.post(
                    f"{base_url(self.addr)}/announce",
                    data=json.dumps(
                        {"info_hash": h.hex, "peer": me.to_dict()}
                    ),
                    deadline=deadline,
                )
        except DeadlineExceeded:
            REGISTRY.counter(
                "announce_timeouts_total",
                "Tracker announces abandoned at their total time budget",
            ).inc()
            raise
        doc = json.loads(body)
        return [PeerInfo.from_dict(p) for p in doc["peers"]], float(doc["interval"])

    async def get(self, namespace: str, d: Digest) -> MetaInfo:
        with trace.span("tracker.get_metainfo", digest=d.hex[:12]):
            raw = await self._http.get(
                f"{base_url(self.addr)}/namespace/"
                f"{quote(namespace, safe='')}/blobs/{d.hex}/metainfo"
            )
        return MetaInfo.deserialize(raw)

    async def get_recipe(
        self, namespace: str, d: Digest
    ) -> tuple[ChunkRecipe, str]:
        """The blob's chunk recipe (delta-transfer plane), proxied from
        the origin cluster, plus the serving origin's addr (the
        ``X-Kraken-Origin`` response header; '' when absent) -- where the
        planner aims its byte-range fetches. Raises HTTPError on 404
        (delta disabled or blob unknown): misses are an expected state
        the planner degrades through, so no retries."""
        with trace.span("tracker.get_recipe", digest=d.hex[:12]):
            _status, headers, body = await self._http.request_full(
                "GET",
                f"{base_url(self.addr)}/namespace/"
                f"{quote(namespace, safe='')}/blobs/{d.hex}/recipe",
                retry_5xx=False,
            )
        return ChunkRecipe.deserialize(body), headers.get(
            "X-Kraken-Origin", ""
        )

    async def similar(self, namespace: str, d: Digest) -> list[dict]:
        """Near-duplicate candidates for ``d`` (delta base selection):
        [{"digest": hex, "score": estimated-Jaccard}], best first."""
        with trace.span("tracker.get_similar", digest=d.hex[:12]):
            raw = await self._http.get(
                f"{base_url(self.addr)}/namespace/"
                f"{quote(namespace, safe='')}/blobs/{d.hex}/similar",
                retry_5xx=False,
            )
        return json.loads(raw)["similar"]

    async def close(self) -> None:
        await self._http.close()

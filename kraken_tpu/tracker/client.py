"""Agent-side tracker clients: announce + metainfo fetch.

Mirrors uber/kraken ``tracker/announceclient`` + ``tracker/metainfoclient``
-- upstream paths, unverified; SURVEY.md SS2.4. These implement the
scheduler's ``AnnounceClient`` / ``MetaInfoClient`` protocols.
"""

from __future__ import annotations

import json

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import InfoHash, MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from urllib.parse import quote

from kraken_tpu.utils.httputil import HTTPClient, base_url


class TrackerClient:
    """Both announce and metainfo against one tracker address."""

    def __init__(
        self,
        addr: str,
        peer_id: PeerID,
        ip: str,
        port: int,
        is_origin: bool = False,
        http: HTTPClient | None = None,
    ):
        self.addr = addr
        self.peer_id = peer_id
        self.ip = ip
        self.port = port
        self.is_origin = is_origin
        self._http = http or HTTPClient()

    async def announce(
        self, d: Digest, h: InfoHash, namespace: str, complete: bool
    ) -> tuple[list[PeerInfo], float]:
        me = PeerInfo(
            peer_id=self.peer_id,
            ip=self.ip,
            port=self.port,
            origin=self.is_origin,
            complete=complete,
        )
        body = await self._http.post(
            f"{base_url(self.addr)}/announce",
            data=json.dumps({"info_hash": h.hex, "peer": me.to_dict()}),
        )
        doc = json.loads(body)
        return [PeerInfo.from_dict(p) for p in doc["peers"]], float(doc["interval"])

    async def get(self, namespace: str, d: Digest) -> MetaInfo:
        raw = await self._http.get(
            f"{base_url(self.addr)}/namespace/{quote(namespace, safe='')}/blobs/{d.hex}/metainfo"
        )
        return MetaInfo.deserialize(raw)

    async def close(self) -> None:
        await self._http.close()

"""Peer handout ordering: who an announcer should dial first.

Mirrors uber/kraken ``tracker/peerhandoutpolicy`` (``PriorityPolicy``
ordering the returned peer list, e.g. prefer non-origin complete peers) --
upstream path, unverified; SURVEY.md SS2.4.

Default policy: completeness-first among normal peers, origins last --
origins are the fallback seeders of last resort; spreading load onto agent
peers is the whole point of the P2P mesh.
"""

from __future__ import annotations

import random

from kraken_tpu.core.peer import PeerInfo


def default_priority(peers: list[PeerInfo]) -> list[PeerInfo]:
    """Non-origin complete peers, then incomplete peers, then origins;
    random within a tier (load spreading)."""

    def tier(p: PeerInfo) -> int:
        if p.origin:
            return 2
        return 0 if p.complete else 1

    shuffled = list(peers)
    random.shuffle(shuffled)
    return sorted(shuffled, key=tier)


POLICIES = {"default": default_priority, "completeness": default_priority}


def get_policy(name: str):
    return POLICIES[name]

"""FastCDC content-defined chunking with the rolling-hash pass on TPU.

Absent from the reference (SURVEY.md SS2.6 table): this is north-star new
capability (BASELINE.json config #4) -- chunk Docker layers on content-
defined boundaries so identical file content shifted by tar offsets still
dedupes across layers.

Algorithm (the framework's normative spec; the pure-Python
:func:`chunk_reference` below is the golden oracle for tests):

- 32-bit gear rolling hash: ``h_i = (h_{i-1} << 1) + GEAR[b_i]  (mod 2^32)``.
  Because of the shift, ``h_i`` depends only on the last 32 bytes -- which is
  what makes the TPU pass possible: every position's hash is a *windowed*
  function, so all positions evaluate in parallel as 32 shifted adds over
  the gather ``GEAR[data]``.
- FastCDC normalized chunking: below the average chunk size a *strict* mask
  must hit (fewer cuts), above it a *loose* mask (more cuts); hard
  ``min_size``/``max_size`` bounds. Masks spread bits per the FastCDC paper
  style; here: contiguous high bits of the 32-bit hash.

Two-phase split (SURVEY.md SS7 hard part #4): the TPU computes the rolling
hash and both mask tests for *every* offset in one vector pass (the O(bytes)
work); the host then walks the resulting sparse candidate list applying the
sequential min/avg/max cut policy (O(cuts) work, ~bytes/avg_size items).
The phases compose to exactly the sequential algorithm because the cut
policy never looks at hashes, only candidate positions -- proven against
``chunk_reference`` in tests/test_cdc.py.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from kraken_tpu.ops import next_pow2

_WINDOW = 32  # bytes of history in a 32-bit gear hash

# Deterministic gear function: framework constant, must never change (chunk
# boundaries are a persistent on-disk contract once dedup metadata is
# written). Defined ARITHMETICALLY (murmur-style avalanche of the byte)
# rather than as a lookup table: TPUs have no fast arbitrary gather -- a
# 256-entry table lookup ran the device pass at ~0.1 GB/s, while the same
# dispersion as 6 vector ops runs at memory speed. The table form below is
# derived from the function and is only used by host-side code.
_GEAR_C1 = 0x9E3779B1  # golden-ratio odd constant
_GEAR_C2 = 0x85EBCA77  # murmur3-style mixer


def _gear_fn_py(b: int) -> int:
    """Reference arithmetic gear: byte -> well-dispersed uint32."""
    x = ((b + 1) * _GEAR_C1) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * _GEAR_C2) & 0xFFFFFFFF
    x ^= x >> 13
    return x


GEAR = np.array([_gear_fn_py(i) for i in range(256)], dtype=np.uint32)


@dataclasses.dataclass(frozen=True)
class CDCParams:
    """Chunking parameters. ``avg_size`` must be a power of two."""

    min_size: int = 16 * 1024
    avg_size: int = 64 * 1024
    max_size: int = 256 * 1024
    # Normalization level: strict mask has (log2(avg) + nc) bits, loose has
    # (log2(avg) - nc). nc=2 per the FastCDC paper's recommendation.
    norm: int = 2

    def __post_init__(self):
        if self.avg_size & (self.avg_size - 1):
            raise ValueError(f"avg_size must be a power of two: {self.avg_size}")
        if not self.min_size <= self.avg_size <= self.max_size:
            raise ValueError("require min_size <= avg_size <= max_size")
        if self.min_size < _WINDOW:
            # Below this the vectorized pass (full 32-byte history at every
            # offset) and the sequential reference (hash restarts per chunk)
            # could disagree near chunk starts.
            raise ValueError(f"min_size must be >= {_WINDOW}: {self.min_size}")

    @property
    def bits(self) -> int:
        return self.avg_size.bit_length() - 1

    @property
    def mask_strict(self) -> int:
        return _top_mask(self.bits + self.norm)

    @property
    def mask_loose(self) -> int:
        return _top_mask(self.bits - self.norm)


def _top_mask(nbits: int) -> int:
    """A mask of ``nbits`` high bits of a uint32."""
    nbits = max(0, min(32, nbits))
    return ((1 << nbits) - 1) << (32 - nbits) & 0xFFFFFFFF


# -- pure-Python reference (golden oracle; O(n) python -- tests only) -------


def chunk_reference(data: bytes, params: CDCParams = CDCParams()) -> list[int]:
    """Sequential FastCDC. Returns chunk end offsets (exclusive)."""
    cuts = []
    n = len(data)
    start = 0
    while start < n:
        end = _next_cut_reference(data, start, n, params)
        cuts.append(end)
        start = end
    return cuts


def _next_cut_reference(data: bytes, start: int, n: int, p: CDCParams) -> int:
    remaining = n - start
    if remaining <= p.min_size:
        return n
    h = 0
    limit = min(remaining, p.max_size)
    norm_point = min(p.avg_size, limit)
    # Hash accumulates from the chunk start (matching the vector pass, which
    # has full history; the first min_size bytes are hashed but uncuttable).
    for i in range(limit):
        h = ((h << 1) + int(GEAR[data[start + i]])) & 0xFFFFFFFF
        if i + 1 <= p.min_size:
            continue
        mask = p.mask_strict if i + 1 <= norm_point else p.mask_loose
        if (h & mask) == 0:
            return start + i + 1
    return start + limit


# -- TPU vector pass --------------------------------------------------------


def _gear_fn_vec(b_u32: jax.Array) -> jax.Array:
    """Vectorized arithmetic gear (exactly :func:`_gear_fn_py`)."""
    x = (b_u32 + np.uint32(1)) * np.uint32(_GEAR_C1)
    x = x ^ (x >> np.uint32(15))
    x = x * np.uint32(_GEAR_C2)
    return x ^ (x >> np.uint32(13))


@functools.partial(jax.jit, static_argnames=("mask_s", "mask_l"))
def _gear_candidates(data_u8: jax.Array, mask_s: int, mask_l: int):
    """Rolling gear hash at every offset + both mask tests.

    data_u8: [L] uint8. Returns (strict, loose): [L] bool arrays where
    ``strict[i]`` means the hash of the 32-byte window ending at ``i``
    (inclusive) hits the strict mask.

    The windowed form: h_i = sum_{j=0..31} gear(b_{i-j}) << j -- a 32-tap
    correlation with weights 2^j. Evaluated by LOG-DOUBLING in 5 steps
    instead of 31 shifted adds: after step k every position holds its
    last-2^k-term partial sum H_k[i] = sum_{j<2^k} g[i-j] << j, and
    H_{k+1}[i] = H_k[i] + (H_k[i - 2^k] << 2^k). Same uint32 wraparound
    arithmetic, 6x fewer strided passes; measured 4.9 -> 9.8 GB/s/chip on
    v5e (2x -- the remaining cost is the per-step buffer materialization,
    not op count; PERF.md).
    """
    g = _gear_fn_vec(data_u8.astype(jnp.uint32))  # [L] uint32
    n = g.shape[0]
    h = jnp.concatenate([jnp.zeros(_WINDOW - 1, dtype=jnp.uint32), g])
    step = 1
    while step < _WINDOW:
        shifted = jnp.concatenate(
            [jnp.zeros(step, dtype=jnp.uint32), h[:-step]]
        )
        h = h + (shifted << np.uint32(step))
        step *= 2
    h = h[_WINDOW - 1 :]
    strict = (h & np.uint32(mask_s)) == 0
    loose = (h & np.uint32(mask_l)) == 0
    return strict, loose


def _host_select_cuts(
    strict_idx: np.ndarray, loose_idx: np.ndarray, n: int, p: CDCParams
) -> list[int]:
    """Sequential cut selection over sparse candidate positions.

    ``strict_idx``/``loose_idx`` hold positions i where the mask hit; a cut
    at position i ends a chunk at offset i+1. Equivalence with the
    sequential reference holds because candidates are only taken at offsets
    > min_size >= _WINDOW past the chunk start, where the 32-byte gear
    window lies entirely inside the current chunk -- so the full-history
    hash of the vector pass equals the restarted hash of the reference.
    """
    cuts: list[int] = []
    start = 0
    while start < n:
        remaining = n - start
        if remaining <= p.min_size:
            cuts.append(n)
            break
        limit = min(remaining, p.max_size)
        norm_point = min(p.avg_size, limit)
        # strict zone: offsets (start+min_size, start+norm_point]
        lo = np.searchsorted(strict_idx, start + p.min_size)
        hi = np.searchsorted(strict_idx, start + norm_point - 1, side="right")
        if lo < hi:
            end = int(strict_idx[lo]) + 1
        else:
            # loose zone: offsets (start+norm_point, start+limit]
            lo = np.searchsorted(loose_idx, start + norm_point)
            hi = np.searchsorted(loose_idx, start + limit - 1, side="right")
            end = int(loose_idx[lo]) + 1 if lo < hi else start + limit
        cuts.append(end)
        start = end
    return cuts


# Large blobs run the vector pass in fixed-size segments: the gear hash at
# position i depends only on bytes [i-31, i], so segments with a 31-byte
# left overlap produce bit-identical candidates to one whole-blob pass
# while bounding device/host memory to O(segment) (the u32 intermediates
# are 4-8x the byte count -- a whole-blob pass on a 10 GiB layer would
# materialize tens of GB).
_SEGMENT = 4 * 1024 * 1024


def _candidate_indices(
    arr: np.ndarray, n: int, params: CDCParams
) -> tuple[np.ndarray, np.ndarray]:
    """Global strict/loose candidate positions over ``arr[:n]``."""
    if n > _SEGMENT and jax.devices()[0].platform == "tpu":
        # TPU + enough bytes to amortize: the Pallas kernel (VMEM-
        # resident doubling, ~43 GB/s/chip chained vs ~10 for the XLA
        # path on v5e; bit-identical candidates). Allowlist on the
        # DEVICE platform (like parallel/hashplane.py's
        # mesh.devices.flat[0].platform): experimental TPU PJRT plugins
        # still report device platform "tpu" (verified live on the axon
        # rig), while non-TPU accelerators (gpu, neuron, ...) -- where
        # the pltpu BlockSpecs cannot lower -- fall through to XLA.
        from kraken_tpu.ops.cdc_pallas import candidate_indices_pallas

        return candidate_indices_pallas(
            arr, n, params.mask_strict, params.mask_loose
        )
    if n <= _SEGMENT:
        # Small blobs: bucket to the next power of two (bounded jit cache).
        # Zero-pad bytes cannot create in-range candidates because only
        # positions < n are kept.
        padded = next_pow2(n)
        if padded != n:
            arr = np.concatenate([arr[:n], np.zeros(padded - n, dtype=np.uint8)])
        else:
            # Copy: jnp.asarray on CPU may alias the numpy buffer and
            # release it asynchronously; callers hand us mmap-backed views
            # whose close() must not race a device transfer (BufferError).
            arr = np.array(arr[:n], copy=True)
        strict, loose = _gear_candidates(
            jnp.asarray(arr), params.mask_strict, params.mask_loose
        )
        return (
            np.flatnonzero(np.asarray(strict)[:n]),
            np.flatnonzero(np.asarray(loose)[:n]),
        )
    buf_len = _SEGMENT + _WINDOW - 1  # one fixed jit shape for every segment
    strict_parts: list[np.ndarray] = []
    loose_parts: list[np.ndarray] = []
    buf = np.zeros(buf_len, dtype=np.uint8)
    for s in range(0, n, _SEGMENT):
        lo = max(0, s - (_WINDOW - 1))
        seg = arr[lo : min(s + _SEGMENT, n)]
        buf[: len(seg)] = seg
        buf[len(seg) :] = 0
        strict, loose = _gear_candidates(
            jnp.asarray(buf), params.mask_strict, params.mask_loose
        )
        local = slice(s - lo, len(seg))  # valid, non-overlap positions
        strict_parts.append(np.flatnonzero(np.asarray(strict)[local]) + s)
        loose_parts.append(np.flatnonzero(np.asarray(loose)[local]) + s)
    return np.concatenate(strict_parts), np.concatenate(loose_parts)


def chunk(data: bytes | memoryview, params: CDCParams = CDCParams()) -> list[int]:
    """Content-defined chunk boundaries (end offsets, exclusive).

    TPU vector pass for the hashes (segmented: O(segment) memory for any
    blob size) + host scan for the cut policy; exactly equal to
    :func:`chunk_reference`.
    """
    view = memoryview(data)
    n = len(view)
    if n == 0:
        return []
    arr = np.frombuffer(view, dtype=np.uint8)
    strict_idx, loose_idx = _candidate_indices(arr, n, params)
    return _host_select_cuts(strict_idx, loose_idx, n, params)


def spans_from_cuts(cuts) -> list[tuple[int, int]]:
    """Cut end-offsets (exclusive, ascending) -> (start, end) spans."""
    spans = []
    start = 0
    for end in cuts:
        spans.append((start, int(end)))
        start = int(end)
    return spans


def chunk_spans(
    data: bytes | memoryview, params: CDCParams = CDCParams()
) -> list[tuple[int, int]]:
    """(start, end) spans for each chunk."""
    return spans_from_cuts(chunk(data, params))


def chunk_host(
    data: bytes | memoryview | np.ndarray, params: CDCParams = CDCParams()
) -> np.ndarray:
    """Host-plane chunker: cut end-offsets WITHOUT touching the device.

    For streaming workloads where the bytes never visit the chip (origin
    dedup scans over backend reads, the 100+ GB corpus bench): the native
    C chunker when built (~1.5 GB/s/core), else a NumPy evaluation of the
    same windowed-gear candidates + the shared host cut policy. Both are
    bit-identical to :func:`chunk_reference` (tests/test_native.py,
    tests/test_cdc.py)."""
    arr = np.frombuffer(memoryview(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data
    n = arr.size
    if n == 0:
        return np.empty(0, dtype=np.uint64)
    from kraken_tpu.native import cdc_chunk_native

    cuts = cdc_chunk_native(
        arr, params.min_size, params.avg_size, params.max_size,
        params.mask_strict, params.mask_loose,
    )
    if cuts is not None:
        return cuts
    # NumPy fallback: the same h_i = sum_j gear(b_{i-j}) << j windowed
    # form as the device pass (uint32 wraparound matches the sequential
    # (h << 1) + gear accumulation for positions with full 32-byte
    # history -- the only positions the cut policy may select past
    # min_size). SEGMENTED with a 31-byte overlap like _candidate_indices:
    # the u32 intermediates are 8x the byte count, and a whole-buffer
    # pass on a 10 GiB layer would materialize ~80 GB.
    strict_parts: list[np.ndarray] = []
    loose_parts: list[np.ndarray] = []
    ms = np.uint32(params.mask_strict)
    ml = np.uint32(params.mask_loose)
    for s in range(0, n, _SEGMENT):
        lo = max(0, s - (_WINDOW - 1))
        seg = arr[lo : min(s + _SEGMENT, n)]
        g = GEAR[seg]
        # Same log-doubling as the device paths: 5 shifted adds, not 31.
        h = g.copy()
        step = 1
        while step < min(_WINDOW, len(seg)):
            h[step:] += h[:-step].copy() << np.uint32(step)
            step *= 2
        local = h[s - lo :]
        strict_parts.append(np.flatnonzero((local & ms) == 0) + s)
        loose_parts.append(np.flatnonzero((local & ml) == 0) + s)
    return np.asarray(
        _host_select_cuts(
            np.concatenate(strict_parts), np.concatenate(loose_parts),
            n, params,
        ),
        dtype=np.uint64,
    )

"""Pallas TPU kernel for batched SHA-256 -- the tuned metainfo-gen path.

Why a kernel (SURVEY.md SS7 hard part #1): the portable XLA scan in
:mod:`kraken_tpu.ops.sha256` pays a loop-iteration overhead per 64-byte
block (the carry bounces through HBM and every iteration is a separate
fused-kernel launch), which caps throughput far below the VPU's integer
rate. Here the whole block chain runs inside one ``pallas_call``:

- grid = (piece_tiles, blocks). Pallas revisits the same output block for
  every ``b`` step of a tile, so the running [8, N] hash state lives in
  VMEM for the whole chain -- written back to HBM once per tile.
- the input is pre-packed (one XLA transpose) to [T, B, 16, N] uint32 so
  each grid step's DMA is one contiguous [16, N] slab (64 KiB at N=1024);
  Pallas double-buffers these loads against compute automatically.
- the 48 schedule extensions + 64 rounds are fully unrolled straight-line
  vector ops on [N]-wide uint32 lanes (N=1024 = a full 8x128 VPU tile per
  op). Unlike XLA:CPU, Mosaic compiles the ~1300-op body without
  pathological simplification passes.

All parallelism is cross-piece: SHA-256's chain serializes blocks within a
piece, so pieces are the batch axis and the block axis is the grid's inner
sequential dimension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kraken_tpu.ops.sha256 import _H0, _K, _pack_be_u32, _pad_block_for

# Pieces per grid tile, laid out as an explicit (sublane, lane) = (8, 128)
# VPU tile so every round op maps to whole vector registers. VMEM per grid
# step: in block KB*16*N*4 = 512 KiB (x2 double buffer) + state 32 KiB.
_SUB = 8
_LANES = 128
N_TILE = _SUB * _LANES
# Blocks folded per grid step: amortizes per-step pipeline overhead (the
# block chain is ~16k steps/tile for 4 MiB pieces if KB=1).
_KB = 8


def _rotr(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _make_sha256_kernel(nb_real: int):
    """Build the grid-step kernel for a chain of ``nb_real`` blocks.

    Each step folds ``_KB`` consecutive blocks of every piece in tile ``t``
    into the running state. blk_ref: [1, _KB, 16, 8, 128]; out_ref:
    [1, 8, 8, 128] (revisited across the block-group axis -- carries the
    state in VMEM).

    The message schedule runs as a 16-word ring interleaved into the
    rounds (w[i+16] = w[i] + s0(w[i+1]) + w[i+9] + s1(w[i+14]) computed in
    place right after round i consumes w[i]), keeping ~24 vector registers
    live instead of 72 -- a fully materialized 64-entry schedule spills.
    """

    def kernel(blk_ref, out_ref):
        b = pl.program_id(1)

        @pl.when(b == 0)
        def _init():
            for i in range(8):
                out_ref[0, i, :, :] = jnp.full((_SUB, _LANES), _H0[i], jnp.uint32)

        state = [out_ref[0, i, :, :] for i in range(8)]
        for kb in range(_KB):
            w = [blk_ref[0, kb, j, :, :] for j in range(16)]
            a, bb, c, d, e, f, g, h = state
            for i in range(64):
                wi = w[i % 16]
                s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
                ch = (e & f) ^ (~e & g)
                t1 = h + s1 + ch + np.uint32(_K[i]) + wi
                s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
                maj = (a & bb) ^ (a & c) ^ (bb & c)
                a, bb, c, d, e, f, g, h = t1 + s0 + maj, a, bb, c, d + t1, e, f, g
                if i < 48:
                    w15 = w[(i + 1) % 16]
                    w2 = w[(i + 14) % 16]
                    e0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
                    e1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
                    w[i % 16] = wi + e0 + w[(i + 9) % 16] + e1
            if (nb_real % _KB) and kb >= nb_real % _KB:
                # Zero-padding blocks past the real chain must not fold in.
                # kb position is only padding in the LAST group; elsewhere
                # it's always real (static bound check keeps it free).
                valid = (b + 1) * _KB <= nb_real
                new = [jnp.where(valid, s + v, s)
                       for s, v in zip(state, (a, bb, c, d, e, f, g, h))]
            else:
                new = [s + v for s, v in zip(state, (a, bb, c, d, e, f, g, h))]
            state = new

        for i in range(8):
            out_ref[0, i, :, :] = state[i]

    return kernel


@functools.partial(jax.jit, static_argnames=("unpadded_blocks", "interpret"))
def sha256_tiles(
    data_u8: jax.Array,
    pad_block: jax.Array,
    unpadded_blocks: int,
    interpret: bool | None = None,
):
    """Hash T*N_TILE equal-length pieces on the Pallas path.

    data_u8: [M, P] uint8 with M % N_TILE == 0 and P = unpadded_blocks * 64;
    pad_block: [16] uint32 shared SHA padding block. Returns [M, 8] uint32.

    ``interpret=None`` picks interpret mode iff the default backend is CPU;
    pass it explicitly when placing the call on a non-default platform
    (e.g. a virtual CPU mesh while a real TPU is attached).
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    m = data_u8.shape[0]
    t = m // N_TILE
    nb = unpadded_blocks + 1  # + shared padding block

    # Pack bytes to big-endian words and lay out [T, B, 16, 8, 128] so the
    # kernel's per-step DMA is contiguous and each word is a full VPU tile.
    words = _pack_be_u32(data_u8.reshape(m, unpadded_blocks, 64))  # [M, B0, 16]
    words = words.reshape(t, N_TILE, unpadded_blocks, 16).transpose(0, 2, 3, 1)
    words = words.reshape(t, unpadded_blocks, 16, _SUB, _LANES)
    pad = jnp.broadcast_to(
        pad_block[None, None, :, None, None], (t, 1, 16, _SUB, _LANES)
    )
    words = jnp.concatenate([words, pad], axis=1)  # [T, B, 16, 8, 128]

    # Pad the block axis to whole _KB groups (kernel skips the zero blocks).
    ngroups = (nb + _KB - 1) // _KB
    if ngroups * _KB != nb:
        words = jnp.concatenate(
            [
                words,
                jnp.zeros((t, ngroups * _KB - nb, 16, _SUB, _LANES), jnp.uint32),
            ],
            axis=1,
        )

    out = pl.pallas_call(
        _make_sha256_kernel(nb),
        # Interpret mode on CPU: the kernel logic stays testable on the
        # virtual-device suite; real TPUs compile via Mosaic.
        interpret=interpret,
        grid=(t, ngroups),
        in_specs=[
            pl.BlockSpec(
                (1, _KB, 16, _SUB, _LANES), lambda ti, bi: (ti, bi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, 8, _SUB, _LANES), lambda ti, bi: (ti, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((t, 8, _SUB, _LANES), jnp.uint32),
    )(words)
    return out.reshape(t, 8, N_TILE).transpose(0, 2, 1).reshape(m, 8)


def hash_pieces_device(
    data_u8: jax.Array, piece_length: int, interpret: bool | None = None
) -> jax.Array:
    """Device-resident uniform-piece hashing via the kernel.

    data_u8: [M, piece_length] uint8 (any M -- padded up to N_TILE
    internally); returns [M, 8] uint32 digest words. piece_length must be a
    multiple of 64.
    """
    if piece_length % 64:
        raise ValueError("pallas path requires piece_length % 64 == 0")
    m = data_u8.shape[0]
    pad_rows = (-m) % N_TILE
    if pad_rows:
        data_u8 = jnp.concatenate(
            [data_u8, jnp.zeros((pad_rows, piece_length), dtype=jnp.uint8)]
        )
    pad = jnp.asarray(_pad_block_for(piece_length))
    return sha256_tiles(data_u8, pad, piece_length // 64, interpret=interpret)[:m]

"""Pallas TPU kernels for batched SHA-256 -- the tuned metainfo-gen path.

Why a kernel (SURVEY.md SS7 hard part #1): the portable XLA scan in
:mod:`kraken_tpu.ops.sha256` pays a loop-iteration overhead per 64-byte
block (the carry bounces through HBM and every iteration is a separate
fused-kernel launch), which caps throughput far below the VPU's integer
rate. Here the whole block chain runs inside one ``pallas_call``:

- grid = (piece_tiles, block_groups). Pallas revisits the same output
  block for every ``b`` step of a tile, so the running [8, N] hash state
  lives in VMEM for the whole chain -- written back to HBM once per tile.
- the 48 schedule extensions + 64 rounds are fully unrolled straight-line
  vector ops on [N]-wide uint32 lanes (N=1024 = a full 8x128 VPU tile per
  op). Unlike XLA:CPU, Mosaic compiles the ~6k-op body without
  pathological simplification passes.
- the message schedule runs as a 16-word ring (w[i+16] computed in place
  right after round i consumes w[i]), keeping ~24 vector registers live
  instead of 72 -- a fully materialized 64-entry schedule spills.

All parallelism is cross-piece: SHA-256's chain serializes blocks within a
piece, so pieces are the batch axis and the block axis is the grid's inner
sequential dimension.

Two input layouts (PERF.md has the measured analysis, v5e 2026-07-29):

- **natural** ``[M, piece_len] uint8`` -- what the store hands over. The
  kernel transposes each [N_TILE, _KB*64] BYTE slab in VMEM (u8
  granularity) and recombines the four byte planes into big-endian words
  with vector shifts -- the BE combine is the byteswap, for free.
  **~75 GB/s/chip** measured (median of repeated runs, r3). The round-2
  u32-word transpose managed only ~18: Mosaic's 32-bit transpose was the
  binding constraint; the u8 transpose of the same bytes runs ~4x faster
  and the u16 variant sits between (~22). Older alternatives -- per-
  sublane-group square transposes (14), MXU byte-plane transpose via
  identity matmul (13.8), XLA pre-transpose (10.7), two-pass repack
  kernel (15.6) -- all slower still.
- **packed** ``[T, NB, 16, 8, 128] uint32`` big-endian word-major tiles,
  produced at feed time by the native host packer
  (:mod:`kraken_tpu.native`, AVX-512 blocked transpose). The kernel then
  does pure rounds: **~92 GB/s/chip** measured. Worth it only when the
  feeder host has the cores to pack at line rate; the u8 natural path
  made this optional rather than mandatory for >=20 GB/s.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kraken_tpu.ops.sha256 import _H0, _K, _pad_block_for

# Pieces per grid tile, laid out as an explicit (sublane, lane) = (8, 128)
# VPU tile so every round op maps to whole vector registers. VMEM per grid
# step: in block KB*16*N*4 = 512 KiB (x2 double buffer) + state 32 KiB.
_SUB = 8
_LANES = 128
N_TILE = _SUB * _LANES
# Blocks folded per grid step: amortizes per-step pipeline overhead (the
# block chain is ~16k steps/tile for 4 MiB pieces if KB=1). Swept 8/16/32
# on v5e: flat at ~18 GB/s for the natural path; 8 keeps VMEM small.
_KB = 8


def _rotr(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _bswap32(x):
    """LE device word -> BE SHA word (vector shifts; ~6 VPU ops)."""
    return (
        ((x & np.uint32(0xFF)) << np.uint32(24))
        | ((x & np.uint32(0xFF00)) << np.uint32(8))
        | ((x >> np.uint32(8)) & np.uint32(0xFF00))
        | (x >> np.uint32(24))
    )


def _rounds64(state, wget):
    """One SHA-256 compression (fully unrolled, 16-word schedule ring).

    ``state``: list of 8 [_SUB, _LANES] uint32 tiles; ``wget(j)`` returns
    message word j as a tile. Returns the post-feed-forward state.
    """
    a, b, c, d, e, f, g, h = state
    w = [wget(j) for j in range(16)]
    for i in range(64):
        wi = w[i % 16]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        # ch/maj in their 3-op/4-op forms (vs the textbook 4/5).
        # Measured neutral on v5e -- Mosaic strength-reduces the textbook
        # forms -- kept because fewer ops can't hurt other backends.
        ch = g ^ (e & (f ^ g))
        t1 = h + s1 + ch + np.uint32(_K[i]) + wi
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & (b ^ c)) ^ (b & c)
        a, b, c, d, e, f, g, h = t1 + s0 + maj, a, b, c, d + t1, e, f, g
        if i < 48:
            w15 = w[(i + 1) % 16]
            w2 = w[(i + 14) % 16]
            e0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
            e1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
            w[i % 16] = wi + e0 + w[(i + 9) % 16] + e1
    return [s + v for s, v in zip(state, (a, b, c, d, e, f, g, h))]


def _make_kernel(nb_real: int, pad_words: np.ndarray, packed: bool):
    """Grid-step kernel for a chain of ``nb_real`` data blocks.

    The shared SHA padding block is folded from compile-time constants
    (``pad_words``) after the last real block -- it never exists in HBM.
    ``packed=False``: blk_ref is a natural [1, N_TILE, _KB*64] uint8 BYTE
    slab, transposed in VMEM at u8 granularity. ``packed=True``: blk_ref
    is pre-packed [1, _KB, 16, _SUB, _LANES] BE words -- no relayout.
    out_ref: [1, 8, _SUB, _LANES], revisited across the block-group axis
    (carries the running state in VMEM).
    """
    ngroups = (nb_real + _KB - 1) // _KB

    def kernel(blk_ref, out_ref):
        b = pl.program_id(1)

        @pl.when(b == 0)
        def _init():
            for i in range(8):
                out_ref[0, i, :, :] = jnp.full((_SUB, _LANES), _H0[i], jnp.uint32)

        state = [out_ref[0, i, :, :] for i in range(8)]
        if not packed:
            # Piece-major -> word-major as ONE up-front BYTE transpose.
            # Granularity matters enormously on v5e (measured r3, same
            # kernel otherwise): u8 transpose ~68 GB/s end-to-end, u16
            # ~22, u32 ~18. Recombining the four byte planes into
            # big-endian words costs 3 shifts + 3 ors per word and IS the
            # byteswap -- the LE->BE conversion falls out of plane order.
            t8 = jnp.transpose(blk_ref[0], (1, 0)).reshape(
                _KB, 16, 4, _SUB, _LANES
            )

            def _word(kb, j):
                b0 = t8[kb, j, 0].astype(jnp.uint32)
                b1 = t8[kb, j, 1].astype(jnp.uint32)
                b2 = t8[kb, j, 2].astype(jnp.uint32)
                b3 = t8[kb, j, 3].astype(jnp.uint32)
                return (
                    (b0 << np.uint32(24))
                    | (b1 << np.uint32(16))
                    | (b2 << np.uint32(8))
                    | b3
                )

        for kb in range(_KB):
            if packed:
                new = _rounds64(
                    state, lambda j, kb=kb: blk_ref[0, kb, j, :, :]
                )
            else:
                new = _rounds64(
                    state, lambda j, kb=kb: _word(kb, j)
                )
            if (nb_real % _KB) and kb >= nb_real % _KB:
                # A position past the real chain only occurs in the final
                # (ragged) group; elsewhere the static bound keeps it free.
                valid = (b + 1) * _KB <= nb_real
                state = [jnp.where(valid, nv, s) for nv, s in zip(new, state)]
            else:
                state = new

        @pl.when(b == ngroups - 1)
        def _fold_pad():
            st = _rounds64(
                state,
                lambda j: jnp.full((_SUB, _LANES), np.uint32(pad_words[j]),
                                   jnp.uint32),
            )
            for i in range(8):
                out_ref[0, i, :, :] = st[i]

        @pl.when(b != ngroups - 1)
        def _store():
            for i in range(8):
                out_ref[0, i, :, :] = state[i]

    return kernel


def _resolve_interpret(interpret: bool | None) -> bool:
    # interpret=None picks interpret mode iff the default backend is CPU;
    # pass it explicitly when placing the call on a non-default platform
    # (e.g. a virtual CPU mesh while a real TPU is attached).
    return jax.default_backend() == "cpu" if interpret is None else interpret


@functools.partial(jax.jit, static_argnames=("unpadded_blocks", "interpret"))
def sha256_tiles(
    data_u8: jax.Array,
    pad_block: jax.Array,
    unpadded_blocks: int,
    interpret: bool | None = None,
):
    """Hash T*N_TILE equal-length pieces from the NATURAL layout.

    data_u8: [M, P] uint8 with M % N_TILE == 0 and P = unpadded_blocks * 64;
    pad_block: [16] uint32 shared SHA padding block (kept for API
    stability; the kernel folds compile-time constants). Returns [M, 8]
    uint32 digest words.
    """
    interpret = _resolve_interpret(interpret)
    m = data_u8.shape[0]
    t = m // N_TILE
    nb = unpadded_blocks
    ngroups = (nb + _KB - 1) // _KB

    # Natural piece-major BYTE slabs, one _KB-block group per grid step --
    # no XLA-side data movement (an XLA pre-transpose was the v1
    # bottleneck: ~12 GB/s); the kernel does the u8 relayout in VMEM.
    slabs = data_u8.reshape(t, N_TILE, nb * 64)
    if nb % _KB:
        # Pad the block axis so the final (masked) grid group has a real
        # slab to DMA; the kernel's validity mask ignores the content.
        slabs = jnp.pad(
            slabs, ((0, 0), (0, 0), (0, (ngroups * _KB - nb) * 64))
        )

    pad_words = np.asarray(_pad_block_for(nb * 64), dtype=np.uint32)

    out = pl.pallas_call(
        _make_kernel(nb, pad_words, packed=False),
        interpret=interpret,
        grid=(t, ngroups),
        in_specs=[
            pl.BlockSpec(
                (1, N_TILE, _KB * 64), lambda ti, bi: (ti, 0, bi),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, 8, _SUB, _LANES), lambda ti, bi: (ti, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((t, 8, _SUB, _LANES), jnp.uint32),
    )(slabs)
    return out.reshape(t, 8, N_TILE).transpose(0, 2, 1).reshape(m, 8)


@functools.partial(jax.jit, static_argnames=("unpadded_blocks", "interpret"))
def sha256_packed_tiles(
    packed: jax.Array,
    unpadded_blocks: int,
    interpret: bool | None = None,
):
    """Hash pieces already in the PACKED word-major layout.

    packed: [T, NB, 16, 8, 128] uint32 big-endian words from
    :func:`kraken_tpu.native.pack_tiles` with NB = ceil(unpadded_blocks /
    _KB) * _KB (trailing blocks ignored). Returns [T*N_TILE, 8] uint32.
    Pure rounds, no relayout: ~92 GB/s/chip measured on v5e.
    """
    interpret = _resolve_interpret(interpret)
    t = packed.shape[0]
    nb = unpadded_blocks
    ngroups = (nb + _KB - 1) // _KB
    pad_words = np.asarray(_pad_block_for(nb * 64), dtype=np.uint32)

    out = pl.pallas_call(
        _make_kernel(nb, pad_words, packed=True),
        interpret=interpret,
        grid=(t, ngroups),
        in_specs=[
            pl.BlockSpec(
                (1, _KB, 16, _SUB, _LANES), lambda ti, bi: (ti, bi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, 8, _SUB, _LANES), lambda ti, bi: (ti, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((t, 8, _SUB, _LANES), jnp.uint32),
    )(packed)
    return out.reshape(t, 8, N_TILE).transpose(0, 2, 1).reshape(t * N_TILE, 8)


def packed_nb(unpadded_blocks: int) -> int:
    """Block-axis extent of the packed layout for a given chain length."""
    return ((unpadded_blocks + _KB - 1) // _KB) * _KB


def _make_pack_kernel():
    """Relayout-only grid step: natural [1, N_TILE, _KB*64] uint8 slab ->
    packed [1, _KB, 16, _SUB, _LANES] big-endian words. The same in-VMEM
    u8 transpose + byte-plane recombine the natural hash kernel performs,
    emitted as data instead of consumed by rounds -- the ``pack: device``
    alternative to the AVX-512 host packer (kraken_tpu/native)."""

    def kernel(blk_ref, out_ref):
        t8 = jnp.transpose(blk_ref[0], (1, 0)).reshape(
            _KB, 16, 4, _SUB, _LANES
        )
        for kb in range(_KB):
            for j in range(16):
                b0 = t8[kb, j, 0].astype(jnp.uint32)
                b1 = t8[kb, j, 1].astype(jnp.uint32)
                b2 = t8[kb, j, 2].astype(jnp.uint32)
                b3 = t8[kb, j, 3].astype(jnp.uint32)
                out_ref[0, kb, j, :, :] = (
                    (b0 << np.uint32(24))
                    | (b1 << np.uint32(16))
                    | (b2 << np.uint32(8))
                    | b3
                )

    return kernel


@functools.partial(jax.jit, static_argnames=("unpadded_blocks", "interpret"))
def pack_tiles_device(
    data_u8: jax.Array,
    unpadded_blocks: int,
    interpret: bool | None = None,
):
    """On-device pack: natural [M, P] uint8 pieces (M % N_TILE == 0,
    P = unpadded_blocks * 64) -> the packed word-major
    [T, NB, 16, _SUB, _LANES] uint32 layout of
    :func:`kraken_tpu.native.pack_tiles`, with NB = packed_nb(...). Bytes
    transfer to the device in natural layout; the relayout (and the LE->BE
    byteswap it implies) happens on-chip, so the host never spends pack
    cores and the hash pass still runs the pure-rounds packed kernel."""
    interpret = _resolve_interpret(interpret)
    m = data_u8.shape[0]
    t = m // N_TILE
    nb = unpadded_blocks
    ngroups = (nb + _KB - 1) // _KB

    slabs = data_u8.reshape(t, N_TILE, nb * 64)
    if nb % _KB:
        # Zero-pad the block axis: zero bytes pack to zero words, which
        # matches the host packer's zero-filled trailing blocks exactly.
        slabs = jnp.pad(
            slabs, ((0, 0), (0, 0), (0, (ngroups * _KB - nb) * 64))
        )

    return pl.pallas_call(
        _make_pack_kernel(),
        interpret=interpret,
        grid=(t, ngroups),
        in_specs=[
            pl.BlockSpec(
                (1, N_TILE, _KB * 64), lambda ti, bi: (ti, 0, bi),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, _KB, 16, _SUB, _LANES), lambda ti, bi: (ti, bi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (t, ngroups * _KB, 16, _SUB, _LANES), jnp.uint32
        ),
    )(slabs)


def hash_pieces_device_packed(
    data_u8: jax.Array, piece_length: int, interpret: bool | None = None
) -> jax.Array:
    """``pack: device`` hash path: on-device relayout
    (:func:`pack_tiles_device`) feeding the pure-rounds packed kernel.
    data_u8: [M, piece_length] uint8, any M; returns [M, 8] uint32."""
    if piece_length % 64:
        raise ValueError("pallas path requires piece_length % 64 == 0")
    m = data_u8.shape[0]
    pad_rows = (-m) % N_TILE
    if pad_rows:
        data_u8 = jnp.concatenate(
            [data_u8, jnp.zeros((pad_rows, piece_length), dtype=jnp.uint8)]
        )
    packed = pack_tiles_device(
        data_u8, piece_length // 64, interpret=interpret
    )
    return sha256_packed_tiles(
        packed, piece_length // 64, interpret=interpret
    )[:m]


def hash_pieces_device(
    data_u8: jax.Array, piece_length: int, interpret: bool | None = None
) -> jax.Array:
    """Device-resident uniform-piece hashing from the natural layout.

    data_u8: [M, piece_length] uint8 (any M -- padded up to N_TILE
    internally); returns [M, 8] uint32 digest words. piece_length must be a
    multiple of 64.
    """
    if piece_length % 64:
        raise ValueError("pallas path requires piece_length % 64 == 0")
    m = data_u8.shape[0]
    pad_rows = (-m) % N_TILE
    if pad_rows:
        data_u8 = jnp.concatenate(
            [data_u8, jnp.zeros((pad_rows, piece_length), dtype=jnp.uint8)]
        )
    pad = jnp.asarray(_pad_block_for(piece_length))
    return sha256_tiles(data_u8, pad, piece_length // 64, interpret=interpret)[:m]


def hash_packed_pieces(
    data: np.ndarray, piece_length: int, interpret: bool | None = None
) -> jax.Array:
    """Host pack (native AVX-512 when available) + packed-kernel hash.

    data: host [M, piece_length] uint8. The pack replaces the staging copy
    a production feeder performs anyway; see PERF.md for the feed-rate
    math. Returns [M, 8] uint32 digest words on device.
    """
    from kraken_tpu.native import pack_tiles

    if piece_length % 64:
        raise ValueError("pallas path requires piece_length % 64 == 0")
    m = data.shape[0]
    pad_rows = (-m) % N_TILE
    if pad_rows:
        data = np.concatenate(
            [data, np.zeros((pad_rows, piece_length), dtype=np.uint8)]
        )
    nb = packed_nb(piece_length // 64)
    packed = pack_tiles(np.ascontiguousarray(data), nb)
    packed = packed.reshape(-1, nb, 16, _SUB, _LANES)
    return sha256_packed_tiles(
        jnp.asarray(packed), piece_length // 64, interpret=interpret
    )[:m]

"""Batched SHA-256 on TPU -- the system's crypto hot loop, as one big vector op.

The reference hashes pieces one at a time on the CPU (uber/kraken
``lib/metainfogen`` generator loop and ``lib/torrent/storage`` piece verify
-- upstream paths, unverified; see SURVEY.md SS2.3/SS2.2). SHA-256's 64-round
dependency chain cannot be parallelized *within* a message, so the TPU win
comes entirely from the batch axis: thousands of pieces hashed in lockstep,
each round a [N]-wide uint32 vector op on the VPU (8x128 lanes).

Layout: a piece of L bytes is SHA-padded to B = (L+8)//64 + 1 blocks of 16
big-endian uint32 words. We stream pieces to the device as raw uint8 (no
host-side byteswap copy), pack to uint32 on device, and `lax.scan` the
compression function over the block axis with a [N, 8] state carry. Ragged
batches (pieces of different lengths) are handled by per-piece block counts
and masked state updates -- one dispatch, no recompiles per length.

Memory: 10k x 4 MiB pieces = 40 GB, far over HBM. ``hash_pieces`` streams
fixed-size sub-batches; JAX's async dispatch overlaps the host->device copy
of batch i+1 with the compute of batch i.

Shapes are bucketed (N and B rounded up to powers of two) so the jit cache
stays small across varying blob sizes.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from kraken_tpu.core.hasher import DIGEST_SIZE, PieceHasher, register_hasher
from kraken_tpu.core.hasher import record_hash_metrics as _record_hash_metrics
from kraken_tpu.ops import next_pow2 as _next_pow2

# fmt: off
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)
_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)
# fmt: on


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


# Scan unroll factor: balances trace/compile size against loop overhead.
# A fully unrolled 64-round body (~1300 ops) sends XLA:CPU's algebraic
# simplifier into a multi-minute fixpoint loop; unroll=8 compiles in
# seconds on both CPU and TPU while keeping per-step vector work dense.
_UNROLL = 8


def _compress(state: jax.Array, block: jax.Array) -> jax.Array:
    """One SHA-256 compression: state [..., 8], block [..., 16] uint32.

    Both the message-schedule extension (48 steps, 16-word sliding carry)
    and the 64 rounds run as ``lax.scan`` so the compiled graph stays small;
    every step is [batch]-wide uint32 vector work on the VPU.
    """

    def sched_step(carry, _):
        # carry: [..., 16] = w[i-16 .. i-1]
        w15, w2 = carry[..., 1], carry[..., 14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
        new = carry[..., 0] + s0 + carry[..., 9] + s1
        return jnp.concatenate([carry[..., 1:], new[..., None]], axis=-1), new

    _, w_ext = jax.lax.scan(
        sched_step, block, None, length=48, unroll=_UNROLL
    )  # [48, ...]
    w_all = jnp.concatenate([jnp.moveaxis(block, -1, 0), w_ext], axis=0)  # [64, ...]

    def round_step(st, kw):
        k, w = kw
        a, b, c, d, e, f, g, h = st
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k + w
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    st0 = tuple(state[..., i] for i in range(8))
    st, _ = jax.lax.scan(
        round_step, st0, (jnp.asarray(_K), w_all), unroll=_UNROLL
    )
    return jnp.stack([state[..., i] + st[i] for i in range(8)], axis=-1)


def _pack_be_u32(b: jax.Array) -> jax.Array:
    """[..., 4k] uint8 -> [..., k] uint32, big-endian (SHA byte order)."""
    b = b.astype(jnp.uint32).reshape(*b.shape[:-1], -1, 4)
    return (
        (b[..., 0] << np.uint32(24))
        | (b[..., 1] << np.uint32(16))
        | (b[..., 2] << np.uint32(8))
        | b[..., 3]
    )


@functools.partial(jax.jit, static_argnames=("unpadded_blocks",))
def _sha256_uniform(data_u8: jax.Array, pad_block: jax.Array, unpadded_blocks: int):
    """Hash N equal-length pieces whose length is a multiple of 64.

    data_u8: [N, P] uint8 with P = unpadded_blocks * 64; pad_block: [16]
    uint32 -- the shared final SHA padding block (0x80, zeros, bit length).
    Returns [N, 8] uint32 digest words.
    """
    n = data_u8.shape[0]
    blocks = data_u8.reshape(n, unpadded_blocks, 64)

    def body(state, blk_u8):
        return _compress(state, _pack_be_u32(blk_u8)), None

    state = jnp.broadcast_to(jnp.asarray(_H0), (n, 8))
    # scan over the block chain: carry is the [N, 8] running state.
    state, _ = jax.lax.scan(body, state, jnp.swapaxes(blocks, 0, 1))
    return _compress(state, jnp.broadcast_to(pad_block, (n, 16)))


@jax.jit
def _sha256_ragged(blocks_u8: jax.Array, nblocks: jax.Array):
    """Hash N pieces of varying block counts, pre-padded on host.

    blocks_u8: [N, B, 64] uint8 (SHA padding already applied per piece);
    nblocks: [N] int32 -- valid block count per piece. Blocks past a piece's
    count are skipped via masked state update. Returns [N, 8] uint32.
    """
    n = blocks_u8.shape[0]

    def body(state, x):
        i, blk_u8 = x
        new = _compress(state, _pack_be_u32(blk_u8))
        keep = (i < nblocks)[:, None]
        return jnp.where(keep, new, state), None

    state = jnp.broadcast_to(jnp.asarray(_H0), (n, 8))
    idx = jnp.arange(blocks_u8.shape[1], dtype=jnp.int32)
    state, _ = jax.lax.scan(body, state, (idx, jnp.swapaxes(blocks_u8, 0, 1)))
    return state


def _digest_bytes(state_words: jax.Array) -> np.ndarray:
    """[N, 8] uint32 digest words -> [N, 32] uint8 big-endian bytes."""
    w = np.asarray(state_words)
    return w.astype(">u4", order="C").view(np.uint8).reshape(-1, DIGEST_SIZE)


def _pad_block_for(length: int) -> np.ndarray:
    """The final 64-byte SHA padding block for a message of ``length`` bytes,
    valid when length % 64 == 0 (so padding occupies exactly one block)."""
    assert length % 64 == 0
    blk = np.zeros(64, dtype=np.uint8)
    blk[0] = 0x80
    blk[56:] = np.frombuffer((length * 8).to_bytes(8, "big"), dtype=np.uint8)
    return _pack_be_u32_np(blk)


def _pack_be_u32_np(b: np.ndarray) -> np.ndarray:
    return b.reshape(-1, 4).astype(np.uint32) @ np.array(
        [1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint32
    )


def _sha_pad_np(piece: memoryview, nblocks_out: int) -> np.ndarray:
    """SHA-pad one piece into [nblocks_out, 64] uint8 (zero-filled beyond)."""
    ln = len(piece)
    need = (ln + 8) // 64 + 1
    assert need <= nblocks_out
    out = np.zeros((nblocks_out, 64), dtype=np.uint8)
    flat = out.reshape(-1)
    flat[:ln] = np.frombuffer(piece, dtype=np.uint8)
    flat[ln] = 0x80
    flat[need * 64 - 8 : need * 64] = np.frombuffer(
        (ln * 8).to_bytes(8, "big"), dtype=np.uint8
    )
    return out


class JaxPieceHasher(PieceHasher):
    """Batched SHA-256 on the default JAX backend (TPU in production;
    registered as ``tpu`` in the hasher registry).

    ``sub_batch_bytes`` bounds the device working set per dispatch; big blobs
    stream through in sub-batches with async dispatch overlapping transfer
    and compute.
    """

    name = "tpu"

    def __init__(
        self, sub_batch_bytes: int = 256 * 1024 * 1024, use_pallas: bool | None = None
    ):
        self._sub_batch_bytes = sub_batch_bytes
        if use_pallas is None:
            # The Pallas kernel is the tuned path on real accelerators; the
            # portable XLA scan is faster than interpret-mode on CPU.
            use_pallas = jax.default_backend() != "cpu"
        self._use_pallas = use_pallas

    # -- blob -> per-piece digests (origin metainfo-gen hot loop) ----------

    def hash_pieces(self, data: bytes | memoryview, piece_length: int) -> np.ndarray:
        if piece_length <= 0:
            raise ValueError(f"piece_length must be positive: {piece_length}")
        view = memoryview(data)
        total = len(view)
        if total == 0:
            return np.empty((0, DIGEST_SIZE), dtype=np.uint8)
        start = time.perf_counter()
        dispatched_rows = 0  # padded rows actually sent to the device
        n = (total + piece_length - 1) // piece_length
        n_full = total // piece_length

        outs: list[jax.Array] = []
        if n_full and piece_length % 64 == 0:
            # Fast path: full pieces go up as raw uint8, zero host reshaping.
            pad = jnp.asarray(_pad_block_for(piece_length))
            per_batch = max(1, self._sub_batch_bytes // piece_length)
            arr = np.frombuffer(view[: n_full * piece_length], dtype=np.uint8)
            arr = arr.reshape(n_full, piece_length)
            for s in range(0, n_full, per_batch):
                chunk = arr[s : s + per_batch]
                g = len(chunk)
                # Bucket the batch axis (pad rows, slice results) so a short
                # final sub-batch doesn't trigger a fresh compile per blob
                # size.
                gb = min(per_batch, _next_pow2(g))
                dispatched_rows += gb
                if gb != g:
                    chunk = np.concatenate(
                        [chunk, np.zeros((gb - g, piece_length), dtype=np.uint8)]
                    )
                if self._use_pallas:
                    from kraken_tpu.ops.sha256_pallas import hash_pieces_device

                    outs.append(
                        hash_pieces_device(jnp.asarray(chunk), piece_length)[:g]
                    )
                else:
                    outs.append(
                        _sha256_uniform(jnp.asarray(chunk), pad, piece_length // 64)[:g]
                    )
            tail = [view[i * piece_length : total] for i in range(n_full, n)]
        else:
            # Odd piece length: everything through the ragged path.
            tail = [
                view[i * piece_length : min((i + 1) * piece_length, total)]
                for i in range(n)
            ]

        if tail:
            tail_digests = self._hash_batch_raw(tail)
            if outs:
                out = np.concatenate(
                    [_digest_bytes(jnp.concatenate(outs)), tail_digests]
                )
            else:
                out = tail_digests
        else:
            out = _digest_bytes(
                jnp.concatenate(outs) if len(outs) > 1 else outs[0]
            )
        _record_hash_metrics(
            "tpu", total, n, time.perf_counter() - start,
            occupancy=(n_full / dispatched_rows) if dispatched_rows else 1.0,
        )
        return out

    # -- arbitrary piece batch (agent verify hot loop) ---------------------

    def hash_batch(self, pieces: list[bytes | memoryview]) -> np.ndarray:
        if not pieces:
            return np.empty((0, DIGEST_SIZE), dtype=np.uint8)
        start = time.perf_counter()
        out = self._hash_batch_raw(pieces)
        # The agent VERIFY loop is the other north-star hot path: a TPU
        # agent that never moves hasher_bytes_total{hasher="tpu"} is
        # indistinguishable from one silently verifying on the CPU
        # (exactly the gap the live-wire e2e test pins). Recording lives
        # HERE, not in _hash_batch_raw: hash_pieces routes its ragged
        # tail through the raw variant and records the blob's FULL total
        # itself -- metrics here too would double-count the tail.
        _record_hash_metrics(
            "tpu", sum(len(memoryview(p)) for p in pieces), len(pieces),
            time.perf_counter() - start,
        )
        return out

    def _hash_batch_raw(self, pieces: list[bytes | memoryview]) -> np.ndarray:
        if not pieces:
            return np.empty((0, DIGEST_SIZE), dtype=np.uint8)
        views = [memoryview(p) for p in pieces]
        n = len(views)
        # Sort by size so one large piece doesn't force the whole batch to
        # its block count -- each sub-batch group buckets to its own max.
        order = sorted(range(n), key=lambda i: len(views[i]))
        out = np.empty((n, DIGEST_SIZE), dtype=np.uint8)

        s = 0
        while s < n:
            # Grow the group greedily while the padded allocation
            # (pow2(count) rows x largest-piece block bucket) stays within
            # the sub-batch budget; always take at least one piece.
            g = 1
            b_bucket = _next_pow2((len(views[order[s]]) + 8) // 64 + 1)
            while s + g < n:
                nxt = _next_pow2((len(views[order[s + g]]) + 8) // 64 + 1)
                grown = max(b_bucket, nxt)
                if _next_pow2(g + 1) * grown * 64 > self._sub_batch_bytes:
                    break
                b_bucket = grown
                g += 1
            group = order[s : s + g]
            gb = _next_pow2(g)
            blocks = np.zeros((gb, b_bucket, 64), dtype=np.uint8)
            nblocks = np.zeros(gb, dtype=np.int32)
            for i, idx in enumerate(group):
                v = views[idx]
                blocks[i] = _sha_pad_np(v, b_bucket)
                nblocks[i] = (len(v) + 8) // 64 + 1
            digests = _digest_bytes(
                _sha256_ragged(jnp.asarray(blocks), jnp.asarray(nblocks))
            )
            for i, idx in enumerate(group):
                out[idx] = digests[i]
            s += g
        return out


register_hasher("tpu", JaxPieceHasher)

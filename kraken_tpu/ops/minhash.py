"""MinHash sketches + LSH banding: the cross-layer near-duplicate index.

Absent from the reference (SURVEY.md SS2.6): north-star new capability
(BASELINE.json config #5). Each Docker layer is represented by the *set* of
its content-defined chunk fingerprints (from :mod:`kraken_tpu.ops.cdc` +
the SHA-256 plane); near-duplicate layers are found by MinHash similarity
search so the origin can dedup storage and preheat caches.

Math: for a random hash h, P[min_h(A) == min_h(B)] = Jaccard(A, B). A
K-coordinate sketch estimates Jaccard with stderr ~ 1/sqrt(K). The TPU part
is the sketching -- K universal hashes h_k(x) = a_k * x + b_k (mod 2^32,
a_k odd) evaluated over every fingerprint and min-reduced, batched over
layers: one [B, M, K]-shaped vector op instead of a per-layer Python loop.
Candidate retrieval uses classic LSH banding on the host (dict buckets --
pointer-chasing, not TPU work); final scoring (estimated Jaccard between a
query sketch and the full sketch matrix) is again one TPU op: a [N, K]
equality-mean reduce.

Fingerprints are uint32 (first 4 bytes of each chunk's SHA-256). At 1M
chunks per corpus the birthday collision count (~100) is noise at MinHash's
estimation accuracy.
"""

from __future__ import annotations

import functools
from typing import Hashable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kraken_tpu.ops import next_pow2 as _next_pow2


def fingerprints_from_digests(digests: np.ndarray) -> np.ndarray:
    """[N, 32] uint8 chunk digests -> [N] uint32 fingerprints (deduped)."""
    if digests.size == 0:
        return np.empty(0, dtype=np.uint32)
    fp = np.ascontiguousarray(digests[:, :4]).view(">u4").reshape(-1)
    return np.unique(fp.astype(np.uint32))


@functools.partial(jax.jit, static_argnames=())
def _sketch_kernel(fps: jax.Array, mask: jax.Array, a: jax.Array, b: jax.Array):
    """fps [B, M] uint32, mask [B, M] bool, a/b [K] uint32 -> [B, K] uint32.

    h_k(x) = a_k * x + b_k (mod 2^32); masked slots contribute the min
    identity. The [B, M, K] intermediate never materializes in HBM -- XLA
    fuses the multiply-add into the min reduction.
    """
    hashed = fps[:, :, None] * a[None, None, :] + b[None, None, :]  # [B,M,K]
    hashed = jnp.where(mask[:, :, None], hashed, jnp.uint32(0xFFFFFFFF))
    return jnp.min(hashed, axis=1)


@jax.jit
def _score_kernel(query: jax.Array, corpus: jax.Array):
    """query [K] uint32 vs corpus [N, K] -> [N] float32 estimated Jaccard."""
    return jnp.mean((corpus == query[None, :]).astype(jnp.float32), axis=1)


_SCORE_DEVICE_MIN = 4096


def _pad_pow2_rows(arr: np.ndarray) -> np.ndarray:
    """Zero-pad the row axis to a power of two (bounded jit cache)."""
    n = arr.shape[0]
    nb = _next_pow2(n)
    if nb == n:
        return arr
    return np.concatenate(
        [arr, np.zeros((nb - n, arr.shape[1]), dtype=arr.dtype)]
    )


def _score(query: np.ndarray, corpus: np.ndarray) -> np.ndarray:
    """Estimated Jaccard of ``query`` vs each corpus row.

    Small candidate sets (the LSH query path: typically tens of rows)
    score on host -- a device round trip costs more than the compare
    itself, and /similar latency is dominated by it. Large scans (the
    brute-force oracle path) go to the device, padded to a power of two
    so candidate-count churn doesn't retrace."""
    n = corpus.shape[0]
    if n < _SCORE_DEVICE_MIN:
        return np.mean(corpus == query[None, :], axis=1, dtype=np.float32)
    return np.asarray(
        _score_kernel(jnp.asarray(query), jnp.asarray(_pad_pow2_rows(corpus)))
    )[:n]


class MinHasher:
    """K-coordinate MinHash sketcher with deterministic seeded hash params."""

    def __init__(self, num_hashes: int = 128, seed: int = 0):
        if num_hashes <= 0:
            raise ValueError("num_hashes must be positive")
        self.num_hashes = num_hashes
        rng = np.random.default_rng(seed)
        self._a = (rng.integers(0, 1 << 32, size=num_hashes, dtype=np.uint64) | 1).astype(
            np.uint32
        )
        self._b = rng.integers(0, 1 << 32, size=num_hashes, dtype=np.uint64).astype(
            np.uint32
        )

    def sketch(self, fingerprints: np.ndarray) -> np.ndarray:
        """[M] uint32 -> [K] uint32 sketch. Empty set -> all-0xFFFFFFFF."""
        return self.sketch_batch([fingerprints])[0]

    def sketch_batch(self, sets: Sequence[np.ndarray]) -> np.ndarray:
        """Sketch a batch of fingerprint sets -> [B, K] uint32.

        Sets are padded to a shared power-of-two M (jit-cache bounded) with
        masked slots.
        """
        if not sets:
            return np.empty((0, self.num_hashes), dtype=np.uint32)
        b = len(sets)
        bb = _next_pow2(b)  # bucket both axes: bounded jit cache
        m = _next_pow2(max(1, max(len(s) for s in sets)))
        fps = np.zeros((bb, m), dtype=np.uint32)
        mask = np.zeros((bb, m), dtype=bool)
        for i, s in enumerate(sets):
            fps[i, : len(s)] = s
            mask[i, : len(s)] = True
        out = _sketch_kernel(
            jnp.asarray(fps), jnp.asarray(mask), jnp.asarray(self._a), jnp.asarray(self._b)
        )
        return np.asarray(out)[:b]


def estimate_jaccard(sketch_a: np.ndarray, sketch_b: np.ndarray) -> float:
    """Fraction of matching coordinates ~ Jaccard(A, B)."""
    return float(np.mean(sketch_a == sketch_b))


class LSHIndex:
    """Banded LSH over MinHash sketches: O(1)-ish candidate retrieval.

    ``num_bands`` bands of ``K / num_bands`` rows; two sets collide in a
    band with probability J^rows, so the S-curve threshold sits near
    (1/num_bands)^(1/rows). Defaults (128 hashes, 32 bands, 4 rows) put the
    knee around J ~ 0.42.
    """

    def __init__(self, hasher: MinHasher, num_bands: int = 32):
        if hasher.num_hashes % num_bands:
            raise ValueError(
                f"num_bands {num_bands} must divide num_hashes {hasher.num_hashes}"
            )
        self.hasher = hasher
        self.num_bands = num_bands
        self.rows = hasher.num_hashes // num_bands
        self._buckets: list[dict[bytes, list[int]]] = [{} for _ in range(num_bands)]
        self._keys: list[Hashable] = []
        self._sketches: list[np.ndarray] = []
        self._key_idx: dict[Hashable, int] = {}  # live key -> row (latest wins)
        self._removed: set[int] = set()  # tombstoned row indices
        self._corpus: np.ndarray | None = None  # rebuilt lazily on query
        # Device-resident copy of the LIVE rows for brute scans: uploading
        # the corpus per query costs more than the scan (it is O(N*K)
        # bytes). Keyed by a mutation generation so consecutive queries
        # share one upload even under churn (tombstones included).
        self._gen = 0
        self._corpus_dev = None
        self._dev_gen = -1

    def __len__(self) -> int:
        return len(self._keys) - len(self._removed)

    def add(self, key: Hashable, sketch: np.ndarray) -> None:
        if key in self._key_idx:
            # Re-adding replaces: tombstone the old row, or it would stay
            # live in the band buckets forever (unremovable ghost).
            self.remove(key)
        idx = len(self._keys)
        self._keys.append(key)
        self._sketches.append(np.asarray(sketch, dtype=np.uint32))
        self._key_idx[key] = idx
        self._corpus = None
        self._gen += 1
        for band, bucket in enumerate(self._buckets):
            sig = self._sketches[idx][band * self.rows : (band + 1) * self.rows].tobytes()
            bucket.setdefault(sig, []).append(idx)

    def remove(self, key: Hashable) -> bool:
        """Tombstone ``key``: its row leaves every band bucket (so it can
        never be a candidate again); the corpus slot is reclaimed by
        :meth:`_compact` once tombstones dominate, so a churn workload
        (add+delete cycles) stays O(live), not O(ever-added). Returns False
        if ``key`` is not present."""
        idx = self._key_idx.pop(key, None)
        if idx is None:
            return False
        self._removed.add(idx)
        self._gen += 1  # live-row set changed: device cache is stale
        sketch = self._sketches[idx]
        for band, bucket in enumerate(self._buckets):
            sig = sketch[band * self.rows : (band + 1) * self.rows].tobytes()
            rows = bucket.get(sig)
            if rows is not None:
                try:
                    rows.remove(idx)
                except ValueError:
                    pass
                if not rows:
                    del bucket[sig]
        if len(self._removed) > 64 and len(self._removed) * 2 > len(self._keys):
            self._compact()
        return True

    def _compact(self) -> None:
        """Rebuild rows/buckets without tombstones (amortized O(1)/remove)."""
        live = [i for i in range(len(self._keys)) if i not in self._removed]
        keys = [self._keys[i] for i in live]
        sketches = [self._sketches[i] for i in live]
        self._keys, self._sketches = keys, sketches
        self._removed = set()
        self._key_idx = {k: i for i, k in enumerate(keys)}
        self._corpus = None
        self._gen += 1
        self._buckets = [{} for _ in range(self.num_bands)]
        for idx, sketch in enumerate(sketches):
            for band, bucket in enumerate(self._buckets):
                sig = sketch[band * self.rows : (band + 1) * self.rows].tobytes()
                bucket.setdefault(sig, []).append(idx)

    def candidates(self, sketch: np.ndarray) -> set[int]:
        """Indices sharing at least one band signature with ``sketch``."""
        sketch = np.asarray(sketch, dtype=np.uint32)
        out: set[int] = set()
        for band, bucket in enumerate(self._buckets):
            sig = sketch[band * self.rows : (band + 1) * self.rows].tobytes()
            out.update(bucket.get(sig, ()))
        return out

    def query(
        self, sketch: np.ndarray, k: int = 10, min_jaccard: float = 0.0
    ) -> list[tuple[Hashable, float]]:
        """Top-k (key, estimated Jaccard) among LSH candidates."""
        cand = sorted(self.candidates(sketch))
        if not cand:
            return []
        if self._corpus is None:
            self._corpus = np.stack(self._sketches)
        scores = _score(np.asarray(sketch, dtype=np.uint32), self._corpus[cand])
        order = np.argsort(-scores)[:k]
        return [
            (self._keys[cand[i]], float(scores[i]))
            for i in order
            if scores[i] >= min_jaccard
        ]

    def query_brute(
        self, sketch: np.ndarray, k: int = 10
    ) -> list[tuple[Hashable, float]]:
        """Top-k against the *entire* corpus (no LSH) -- one [N, K] TPU op.

        Exact over sketches; used when recall matters more than latency and
        as the oracle for LSH recall tests.
        """
        live = [i for i in range(len(self._keys)) if i not in self._removed]
        if not live:
            return []
        if self._corpus is None:
            self._corpus = np.stack(self._sketches)
        query = np.asarray(sketch, dtype=np.uint32)
        if len(live) >= _SCORE_DEVICE_MIN:
            # Large corpus: scan the cached device copy of the live rows
            # (rebuilt only when the index mutated since the last scan).
            if self._corpus_dev is None or self._dev_gen != self._gen:
                rows = (
                    self._corpus
                    if len(live) == len(self._keys)
                    else self._corpus[live]
                )
                self._corpus_dev = jnp.asarray(_pad_pow2_rows(rows))
                self._dev_gen = self._gen
            scores = np.asarray(
                _score_kernel(jnp.asarray(query), self._corpus_dev)
            )[: len(live)]
        else:
            scores = _score(query, self._corpus[live])
        order = np.argsort(-scores)[:k]
        return [(self._keys[live[i]], float(scores[i])) for i in order]

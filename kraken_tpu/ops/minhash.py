"""MinHash sketches + LSH banding: the cross-layer near-duplicate index.

Absent from the reference (SURVEY.md SS2.6): north-star new capability
(BASELINE.json config #5). Each Docker layer is represented by the *set* of
its content-defined chunk fingerprints (from :mod:`kraken_tpu.ops.cdc` +
the SHA-256 plane); near-duplicate layers are found by MinHash similarity
search so the origin can dedup storage and preheat caches.

Math: for a random hash h, P[min_h(A) == min_h(B)] = Jaccard(A, B). A
K-coordinate sketch estimates Jaccard with stderr ~ 1/sqrt(K). The TPU part
is the sketching -- K universal hashes h_k(x) = a_k * x + b_k (mod 2^32,
a_k odd) evaluated over every fingerprint and min-reduced, batched over
layers: one [B, M, K]-shaped vector op instead of a per-layer Python loop.
Candidate retrieval uses classic LSH banding on the host (dict buckets --
pointer-chasing, not TPU work); final scoring (estimated Jaccard between a
query sketch and the full sketch matrix) is again one TPU op: a [N, K]
equality-mean reduce.

Fingerprints are uint32 (first 4 bytes of each chunk's SHA-256). At 1M
chunks per corpus the birthday collision count (~100) is noise at MinHash's
estimation accuracy.
"""

from __future__ import annotations

import functools
from typing import Hashable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kraken_tpu.ops import next_pow2 as _next_pow2


def fingerprints_from_digests(digests: np.ndarray) -> np.ndarray:
    """[N, 32] uint8 chunk digests -> [N] uint32 fingerprints (deduped)."""
    if digests.size == 0:
        return np.empty(0, dtype=np.uint32)
    fp = np.ascontiguousarray(digests[:, :4]).view(">u4").reshape(-1)
    return np.unique(fp.astype(np.uint32))


@functools.partial(jax.jit, static_argnames=())
def _sketch_kernel(fps: jax.Array, mask: jax.Array, a: jax.Array, b: jax.Array):
    """fps [B, M] uint32, mask [B, M] bool, a/b [K] uint32 -> [B, K] uint32.

    h_k(x) = a_k * x + b_k (mod 2^32); masked slots contribute the min
    identity. The [B, M, K] intermediate never materializes in HBM -- XLA
    fuses the multiply-add into the min reduction.
    """
    hashed = fps[:, :, None] * a[None, None, :] + b[None, None, :]  # [B,M,K]
    hashed = jnp.where(mask[:, :, None], hashed, jnp.uint32(0xFFFFFFFF))
    return jnp.min(hashed, axis=1)


@jax.jit
def _score_kernel(query: jax.Array, corpus: jax.Array):
    """query [K] uint32 vs corpus [N, K] -> [N] float32 estimated Jaccard."""
    return jnp.mean((corpus == query[None, :]).astype(jnp.float32), axis=1)


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_kernel(query: jax.Array, corpus: jax.Array, n_live, k: int):
    """Score + device-side top-k: only 2k scalars leave the chip instead
    of the full [N] score vector (4 MB at a 1M corpus -- the transfer,
    not the scan, dominates brute-query latency on thin links). Padding
    rows (index >= n_live, a traced scalar: no retrace as the index
    churns) are masked to -1 so they can never place."""
    scores = jnp.mean((corpus == query[None, :]).astype(jnp.float32), axis=1)
    scores = jnp.where(
        jnp.arange(corpus.shape[0]) < n_live, scores, jnp.float32(-1.0)
    )
    return jax.lax.top_k(scores, k)


_SCORE_DEVICE_MIN = 4096


def _pad_pow2_rows(arr: np.ndarray) -> np.ndarray:
    """Zero-pad the row axis to a power of two (bounded jit cache)."""
    n = arr.shape[0]
    nb = _next_pow2(n)
    if nb == n:
        return arr
    return np.concatenate(
        [arr, np.zeros((nb - n, arr.shape[1]), dtype=arr.dtype)]
    )


def _score(query: np.ndarray, corpus: np.ndarray) -> np.ndarray:
    """Estimated Jaccard of ``query`` vs each corpus row.

    Small candidate sets (the LSH query path: typically tens of rows)
    score on host -- a device round trip costs more than the compare
    itself, and /similar latency is dominated by it. Large scans (the
    brute-force oracle path) go to the device, padded to a power of two
    so candidate-count churn doesn't retrace."""
    n = corpus.shape[0]
    if n < _SCORE_DEVICE_MIN:
        return np.mean(corpus == query[None, :], axis=1, dtype=np.float32)
    return np.asarray(
        _score_kernel(jnp.asarray(query), jnp.asarray(_pad_pow2_rows(corpus)))
    )[:n]


class MinHasher:
    """K-coordinate MinHash sketcher with deterministic seeded hash params."""

    def __init__(self, num_hashes: int = 128, seed: int = 0):
        if num_hashes <= 0:
            raise ValueError("num_hashes must be positive")
        self.num_hashes = num_hashes
        rng = np.random.default_rng(seed)
        self._a = (rng.integers(0, 1 << 32, size=num_hashes, dtype=np.uint64) | 1).astype(
            np.uint32
        )
        self._b = rng.integers(0, 1 << 32, size=num_hashes, dtype=np.uint64).astype(
            np.uint32
        )

    def sketch(self, fingerprints: np.ndarray) -> np.ndarray:
        """[M] uint32 -> [K] uint32 sketch. Empty set -> all-0xFFFFFFFF."""
        return self.sketch_batch([fingerprints])[0]

    def sketch_batch(self, sets: Sequence[np.ndarray]) -> np.ndarray:
        """Sketch a batch of fingerprint sets -> [B, K] uint32.

        Sets are padded to a shared power-of-two M (jit-cache bounded) with
        masked slots.
        """
        if not sets:
            return np.empty((0, self.num_hashes), dtype=np.uint32)
        b = len(sets)
        bb = _next_pow2(b)  # bucket both axes: bounded jit cache
        m = _next_pow2(max(1, max(len(s) for s in sets)))
        fps = np.zeros((bb, m), dtype=np.uint32)
        mask = np.zeros((bb, m), dtype=bool)
        for i, s in enumerate(sets):
            fps[i, : len(s)] = s
            mask[i, : len(s)] = True
        out = _sketch_kernel(
            jnp.asarray(fps), jnp.asarray(mask), jnp.asarray(self._a), jnp.asarray(self._b)
        )
        return np.asarray(out)[:b]


def estimate_jaccard(sketch_a: np.ndarray, sketch_b: np.ndarray) -> float:
    """Fraction of matching coordinates ~ Jaccard(A, B)."""
    return float(np.mean(sketch_a == sketch_b))


class LSHIndex:
    """Banded LSH over MinHash sketches: O(1)-ish candidate retrieval.

    ``num_bands`` bands of ``K / num_bands`` rows; two sets collide in a
    band with probability J^rows, so the S-curve threshold sits near
    (1/num_bands)^(1/rows). Defaults (128 hashes, 32 bands, 4 rows) put the
    knee around J ~ 0.42.

    **Low-J tier** (round 5, VERDICT r4 weak #1): the primary banding's
    knee leaves below-knee similarity (J in [0.2, 0.42)) nearly invisible
    -- planted retrieval @ J=0.3 measured 0.27 at 1M sets. A second tier
    of ``low_j_bands`` 2-row bands over the sketch's leading hashes
    collides with probability 1-(1-J^2)^bands (~0.95 @ J=0.3 with 32
    bands), pulling the combined S-curve's foot down to ~J=0.2 for a
    bounded cost: candidate volume grows by the corpus's background-J
    mass (scored vectorized anyway) and the band plane grows by
    12 B/set/band. ``low_j_bands=0`` restores the single-tier shape.
    """

    def __init__(
        self,
        hasher: MinHasher,
        num_bands: int = 32,
        low_j_bands: int | None = None,
    ):
        if hasher.num_hashes % num_bands:
            raise ValueError(
                f"num_bands {num_bands} must divide num_hashes {hasher.num_hashes}"
            )
        if low_j_bands is None:  # as many 2-row bands as the sketch allows
            low_j_bands = min(32, hasher.num_hashes // 2)
        if low_j_bands < 0:
            raise ValueError(f"low_j_bands must be >= 0: {low_j_bands}")
        if low_j_bands * 2 > hasher.num_hashes:
            raise ValueError(
                f"low_j_bands {low_j_bands} needs {low_j_bands * 2} hashes, "
                f"sketch has {hasher.num_hashes}"
            )
        self.hasher = hasher
        self.num_bands = num_bands
        self.low_j_bands = low_j_bands
        self.rows = hasher.num_hashes // num_bands
        total = num_bands + low_j_bands
        self._buckets: list[dict[bytes, list[int]]] = [{} for _ in range(total)]
        self._keys: list[Hashable] = []
        self._sketches: list[np.ndarray] = []
        self._key_idx: dict[Hashable, int] = {}  # live key -> row (latest wins)
        self._removed: set[int] = set()  # tombstoned row indices
        self._corpus: np.ndarray | None = None  # rebuilt lazily on query
        # Device-resident copy of the LIVE rows for brute scans: uploading
        # the corpus per query costs more than the scan (it is O(N*K)
        # bytes). Keyed by a mutation generation so consecutive queries
        # share one upload even under churn (tombstones included).
        self._gen = 0
        self._corpus_dev = None
        self._dev_gen = -1

    def __len__(self) -> int:
        return len(self._keys) - len(self._removed)

    def __contains__(self, key: Hashable) -> bool:
        """True when ``key`` is live (added and not removed/evicted)."""
        idx = self._key_idx.get(key)
        return idx is not None and idx not in self._removed

    def _band_key(self, sketch: np.ndarray, band: int) -> bytes:
        """Bucket key for global band index ``band``: primary bands slice
        ``rows`` hashes; low-J tier bands (index >= num_bands) slice 2
        hashes from the sketch's leading coordinates."""
        if band < self.num_bands:
            return sketch[band * self.rows : (band + 1) * self.rows].tobytes()
        j = band - self.num_bands
        return sketch[j * 2 : (j + 1) * 2].tobytes()

    def add(self, key: Hashable, sketch: np.ndarray) -> None:
        if key in self._key_idx:
            # Re-adding replaces: tombstone the old row, or it would stay
            # live in the band buckets forever (unremovable ghost).
            self.remove(key)
        idx = len(self._keys)
        self._keys.append(key)
        self._sketches.append(np.asarray(sketch, dtype=np.uint32))
        self._key_idx[key] = idx
        self._corpus = None
        self._gen += 1
        for band, bucket in enumerate(self._buckets):
            sig = self._band_key(self._sketches[idx], band)
            bucket.setdefault(sig, []).append(idx)

    def remove(self, key: Hashable) -> bool:
        """Tombstone ``key``: its row leaves every band bucket (so it can
        never be a candidate again); the corpus slot is reclaimed by
        :meth:`_compact` once tombstones dominate, so a churn workload
        (add+delete cycles) stays O(live), not O(ever-added). Returns False
        if ``key`` is not present."""
        idx = self._key_idx.pop(key, None)
        if idx is None:
            return False
        self._removed.add(idx)
        self._gen += 1  # live-row set changed: device cache is stale
        sketch = self._sketches[idx]
        for band, bucket in enumerate(self._buckets):
            sig = self._band_key(sketch, band)
            rows = bucket.get(sig)
            if rows is not None:
                try:
                    rows.remove(idx)
                except ValueError:
                    pass
                if not rows:
                    del bucket[sig]
        if len(self._removed) > 64 and len(self._removed) * 2 > len(self._keys):
            self._compact()
        return True

    def _compact(self) -> None:
        """Rebuild rows/buckets without tombstones (amortized O(1)/remove)."""
        live = [i for i in range(len(self._keys)) if i not in self._removed]
        keys = [self._keys[i] for i in live]
        sketches = [self._sketches[i] for i in live]
        self._keys, self._sketches = keys, sketches
        self._removed = set()
        self._key_idx = {k: i for i, k in enumerate(keys)}
        self._corpus = None
        self._gen += 1
        self._buckets = [
            {} for _ in range(self.num_bands + self.low_j_bands)
        ]
        for idx, sketch in enumerate(sketches):
            for band, bucket in enumerate(self._buckets):
                sig = self._band_key(sketch, band)
                bucket.setdefault(sig, []).append(idx)

    def candidates(self, sketch: np.ndarray) -> set[int]:
        """Indices sharing at least one band signature with ``sketch``."""
        sketch = np.asarray(sketch, dtype=np.uint32)
        out: set[int] = set()
        for band, bucket in enumerate(self._buckets):
            sig = self._band_key(sketch, band)
            out.update(bucket.get(sig, ()))
        return out

    def query(
        self, sketch: np.ndarray, k: int = 10, min_jaccard: float = 0.0
    ) -> list[tuple[Hashable, float]]:
        """Top-k (key, estimated Jaccard) among LSH candidates."""
        cand = sorted(self.candidates(sketch))
        if not cand:
            return []
        if self._corpus is None:
            self._corpus = np.stack(self._sketches)
        scores = _score(np.asarray(sketch, dtype=np.uint32), self._corpus[cand])
        order = np.argsort(-scores)[:k]
        return [
            (self._keys[cand[i]], float(scores[i]))
            for i in order
            if scores[i] >= min_jaccard
        ]

    def query_brute(
        self, sketch: np.ndarray, k: int = 10
    ) -> list[tuple[Hashable, float]]:
        """Top-k against the *entire* corpus (no LSH) -- one [N, K] TPU op.

        Exact over sketches; used when recall matters more than latency and
        as the oracle for LSH recall tests.
        """
        live = [i for i in range(len(self._keys)) if i not in self._removed]
        if not live:
            return []
        if self._corpus is None:
            self._corpus = np.stack(self._sketches)
        query = np.asarray(sketch, dtype=np.uint32)
        if len(live) >= _SCORE_DEVICE_MIN:
            # Large corpus: scan the cached device copy of the live rows
            # (rebuilt only when the index mutated since the last scan).
            if self._corpus_dev is None or self._dev_gen != self._gen:
                rows = (
                    self._corpus
                    if len(live) == len(self._keys)
                    else self._corpus[live]
                )
                self._corpus_dev = jnp.asarray(_pad_pow2_rows(rows))
                self._dev_gen = self._gen
            kk = min(k, len(live))
            top_v, top_i = _topk_kernel(
                jnp.asarray(query), self._corpus_dev, len(live), kk
            )
            return [
                (self._keys[live[i]], float(v))
                for i, v in zip(np.asarray(top_i), np.asarray(top_v))
            ]
        scores = _score(query, self._corpus[live])
        order = np.argsort(-scores)[:k]
        return [(self._keys[live[i]], float(scores[i])) for i in order]


def _band_sigs(sketches: np.ndarray, num_bands: int) -> np.ndarray:
    """[N, K] uint32 sketches -> [N, B] uint64 band signatures (FNV-1a
    over each band's rows, vectorized). 64-bit sigs at 1M rows/band give
    ~3e-8 expected accidental collisions -- noise next to LSH's own
    false-candidate rate -- at half the memory of raw 16-byte keys."""
    n, k = sketches.shape
    rows = k // num_bands
    v = sketches.reshape(n, num_bands, rows).astype(np.uint64)
    h = np.full((n, num_bands), 0xCBF29CE484222325, dtype=np.uint64)
    prime = np.uint64(0x100000001B3)
    for r in range(rows):
        h = (h ^ v[:, :, r]) * prime
    return h


class BudgetExceeded(Exception):
    pass


class CompactLSHIndex:
    """Array-backed LSH index for million-set corpora, with a byte budget.

    Same banding math and the same query semantics as :class:`LSHIndex`,
    different storage (that class spends multiple KB/set in per-band dict
    buckets at 1M sets; this one ~1 KB/set all-in):

    - sketches live in ONE growable ``[cap, K]`` uint32 matrix -- no
      per-row Python objects (512 B/set at K=128);
    - each band keeps (sorted uint64 sigs, parallel int32 rows) numpy
      pairs plus an unsorted pending tail; the tail merges in when it
      outgrows ``max(4096, merged/8)``, so lookups are two binary
      searches + a small linear scan, amortized O(N log N) to build;
      12 B/set/band x 32 bands = 384 B/set for the band plane;
    - ``budget_bytes`` caps the accounted footprint; when an add would
      exceed it the OLDEST live rows are evicted (layer churn means old
      sketches are the least likely to be queried) and storage compacted.

    Tombstoned/evicted rows are dropped at merge/compact; ``remove`` and
    re-``add`` share :class:`LSHIndex` semantics (latest add wins).
    """

    def __init__(
        self,
        hasher: MinHasher,
        num_bands: int = 32,
        budget_bytes: int | None = None,
        low_j_bands: int | None = None,
    ):
        if hasher.num_hashes % num_bands:
            raise ValueError(
                f"num_bands {num_bands} must divide num_hashes {hasher.num_hashes}"
            )
        if low_j_bands is None:  # as many 2-row bands as the sketch allows
            low_j_bands = min(32, hasher.num_hashes // 2)
        if low_j_bands < 0:
            raise ValueError(f"low_j_bands must be >= 0: {low_j_bands}")
        if low_j_bands * 2 > hasher.num_hashes:
            raise ValueError(
                f"low_j_bands {low_j_bands} needs {low_j_bands * 2} hashes, "
                f"sketch has {hasher.num_hashes}"
            )
        self.hasher = hasher
        self.num_bands = num_bands
        # Low-J tier: 2-row bands over the leading hashes (see LSHIndex
        # docstring). Band storage below is sized num_bands + low_j_bands;
        # primary bands come first in every per-band array.
        self.low_j_bands = low_j_bands
        self.rows = hasher.num_hashes // num_bands
        self.budget_bytes = budget_bytes
        self.evictions = 0
        total = num_bands + low_j_bands
        self._total_bands = total
        k = hasher.num_hashes
        self._mat = np.empty((1024, k), dtype=np.uint32)
        self._n = 0  # rows used in _mat (live + dead)
        self._alive = np.zeros(1024, dtype=bool)
        self._keys: list[Hashable] = []
        self._key_idx: dict[Hashable, int] = {}
        self._dead = 0
        # Per band: merged (sorted sigs, rows) + pending (unsorted numpy
        # tail, filled to _pend_n). Pending is numpy so the per-query
        # equality scan is SIMD, not a Python loop.
        self._merged: list[tuple[np.ndarray, np.ndarray]] = [
            (np.empty(0, np.uint64), np.empty(0, np.int32))
            for _ in range(total)
        ]
        self._pend_sigs: list[np.ndarray] = [
            np.empty(4096, np.uint64) for _ in range(total)
        ]
        self._pend_rows: list[np.ndarray] = [
            np.empty(4096, np.int32) for _ in range(total)
        ]
        self._pend_n = [0] * total
        # Device-resident live rows for brute scans (see LSHIndex).
        self._gen = 0
        self._dev = None
        self._dev_live: np.ndarray | None = None
        self._dev_gen = -1

    def _all_sigs(self, sketches: np.ndarray) -> np.ndarray:
        """[N, K] sketches -> [N, num_bands + low_j_bands] uint64 sigs
        (primary tier first, then the low-J tier)."""
        sigs = _band_sigs(sketches, self.num_bands)
        if self.low_j_bands:
            lo = _band_sigs(
                sketches[:, : self.low_j_bands * 2], self.low_j_bands
            )
            sigs = np.concatenate([sigs, lo], axis=1)
        return sigs

    def __len__(self) -> int:
        return self._n - self._dead

    def __contains__(self, key: Hashable) -> bool:
        """True when ``key`` is live (added and not removed/evicted)."""
        idx = self._key_idx.get(key)
        return idx is not None and bool(self._alive[idx])

    def set_budget(self, budget_bytes: int | None) -> None:
        """Swap the byte budget live and enforce it NOW, evicting oldest
        live rows if the current footprint exceeds it. The forced-eviction
        bench path (bench_minhash.py, VERDICT r5 weak #4) and the natural
        hook for a future live reload of ``dedup_budget_bytes``."""
        self.budget_bytes = budget_bytes
        if budget_bytes is not None:
            self._enforce_budget()

    # -- storage -----------------------------------------------------------

    def footprint_bytes(self) -> int:
        """Accounted index footprint: the numpy storage exactly, plus a
        ~100 B/key allowance for the Python key list + key->row dict."""
        b = self._mat.nbytes + self._alive.nbytes
        for sigs, rows in self._merged:
            b += sigs.nbytes + rows.nbytes
        for p in self._pend_sigs:
            b += p.nbytes
        for p in self._pend_rows:
            b += p.nbytes
        b += len(self._keys) * 100
        return b

    def _grow(self, need: int) -> None:
        cap = self._mat.shape[0]
        if self._n + need <= cap:
            return
        new_cap = cap
        while new_cap < self._n + need:
            new_cap *= 2
        self._mat = np.concatenate(
            [self._mat, np.empty((new_cap - cap, self._mat.shape[1]),
                                 dtype=np.uint32)]
        )
        self._alive = np.concatenate(
            [self._alive, np.zeros(new_cap - cap, dtype=bool)]
        )

    # Pending tails merge when full. The cap trades amortized merge-sort
    # work against the per-query linear scan of the tail; 64k keeps both
    # small (a 1M-row band re-sorts ~15 times; a query scans <= 64k u64
    # per band, SIMD).
    _PEND_MAX = 65536

    def _pend_cap(self, band: int) -> int:
        return min(
            self._PEND_MAX, max(4096, len(self._merged[band][0]) // 8)
        )

    def _merge_band(self, band: int) -> None:
        n = self._pend_n[band]
        sigs, rows = self._merged[band]
        all_s = np.concatenate([sigs, self._pend_sigs[band][:n]])
        all_r = np.concatenate([rows, self._pend_rows[band][:n]])
        live = self._alive[all_r]  # drop tombstones while we're here
        all_s, all_r = all_s[live], all_r[live]
        order = np.argsort(all_s, kind="stable")
        self._merged[band] = (all_s[order], all_r[order])
        self._pend_n[band] = 0

    def flush(self) -> None:
        """Merge every pending tail. Bulk-load-then-query workloads call
        this once after loading so queries are pure binary search."""
        for band in range(self._total_bands):
            if self._pend_n[band]:
                self._merge_band(band)

    # -- mutation ----------------------------------------------------------

    def add(self, key: Hashable, sketch: np.ndarray) -> None:
        self.add_batch([key], np.asarray(sketch, dtype=np.uint32)[None, :])

    def add_batch(self, keys: Sequence[Hashable], sketches: np.ndarray) -> None:
        """Bulk add: one signature pass + one pending append per band.
        Keys must be unique within the batch (duplicates across batches
        follow re-add semantics: latest wins)."""
        sketches = np.asarray(sketches, dtype=np.uint32)
        if sketches.ndim != 2 or sketches.shape[0] != len(keys):
            raise ValueError("sketches must be [len(keys), K]")
        for key in keys:
            old = self._key_idx.pop(key, None)
            if old is not None and self._alive[old]:
                self._alive[old] = False
                self._dead += 1
        n = len(keys)
        self._grow(n)
        start = self._n
        self._mat[start : start + n] = sketches
        self._alive[start : start + n] = True
        self._n += n
        for i, key in enumerate(keys):
            self._keys.append(key)
            self._key_idx[key] = start + i
        self._gen += 1  # live-row set changed: device cache is stale
        sigs = self._all_sigs(sketches)
        new_rows = np.arange(start, start + n, dtype=np.int32)
        for band in range(self._total_bands):
            self._pend_append(band, sigs[:, band], new_rows)
            if self._pend_n[band] >= self._pend_cap(band):
                self._merge_band(band)
        if self.budget_bytes is not None:
            self._enforce_budget()
        elif self._dead > 64 and self._dead * 2 > self._n:
            self._compact()

    def _pend_append(
        self, band: int, sigs: np.ndarray, rows: np.ndarray
    ) -> None:
        need = self._pend_n[band] + len(sigs)
        buf_s = self._pend_sigs[band]
        if need > len(buf_s):
            cap = max(need, 2 * len(buf_s))
            ns = np.empty(cap, np.uint64)
            nr = np.empty(cap, np.int32)
            ns[: self._pend_n[band]] = buf_s[: self._pend_n[band]]
            nr[: self._pend_n[band]] = self._pend_rows[band][
                : self._pend_n[band]
            ]
            self._pend_sigs[band], self._pend_rows[band] = ns, nr
        self._pend_sigs[band][self._pend_n[band] : need] = sigs
        self._pend_rows[band][self._pend_n[band] : need] = rows
        self._pend_n[band] = need

    def remove(self, key: Hashable) -> bool:
        idx = self._key_idx.pop(key, None)
        if idx is None or not self._alive[idx]:
            return False
        self._alive[idx] = False
        self._dead += 1
        self._gen += 1
        if self._dead > 64 and self._dead * 2 > self._n:
            self._compact()
        return True

    def _compact(self, extra_evict: int = 0) -> None:
        """Rebuild matrix + bands from live rows (oldest ``extra_evict``
        live rows dropped first -- the budget eviction path)."""
        live_rows = np.flatnonzero(self._alive[: self._n])
        if extra_evict:
            evicted = live_rows[:extra_evict]
            self._alive[evicted] = False
            self.evictions += len(evicted)
            live_rows = live_rows[extra_evict:]
        mat = self._mat[live_rows].copy()
        keys = [self._keys[i] for i in live_rows]
        k = self.hasher.num_hashes
        self._n = len(keys)
        cap = max(1024, _next_pow2(self._n))
        self._mat = np.empty((cap, k), dtype=np.uint32)
        self._mat[: self._n] = mat
        self._alive = np.zeros(cap, dtype=bool)
        self._alive[: self._n] = True
        self._keys = keys
        self._key_idx = {key: i for i, key in enumerate(keys)}
        self._dead = 0
        self._gen += 1
        self._merged = [
            (np.empty(0, np.uint64), np.empty(0, np.int32))
            for _ in range(self._total_bands)
        ]
        self._pend_sigs = [
            np.empty(4096, np.uint64) for _ in range(self._total_bands)
        ]
        self._pend_rows = [
            np.empty(4096, np.int32) for _ in range(self._total_bands)
        ]
        self._pend_n = [0] * self._total_bands
        if self._n:
            sigs = self._all_sigs(self._mat[: self._n])
            rows = np.arange(self._n, dtype=np.int32)
            for band in range(self._total_bands):
                order = np.argsort(sigs[:, band], kind="stable")
                self._merged[band] = (sigs[order, band], rows[order])

    def _enforce_budget(self) -> None:
        if self.footprint_bytes() <= self.budget_bytes:
            return
        # Evict oldest live rows, at least 10% of the corpus per pass
        # (avoids thrashing a compaction per add).
        self._compact()  # drop dead rows first; they are free savings
        while self.footprint_bytes() > self.budget_bytes:
            if not len(self):
                # Budget below the empty-index floor (preallocated matrix
                # + pending buffers): no eviction can satisfy it -- a
                # misconfiguration that must be loud, not a silently
                # always-empty index.
                raise BudgetExceeded(
                    f"budget {self.budget_bytes} B is below the empty-"
                    f"index floor ({self.footprint_bytes()} B)"
                )
            self._compact(extra_evict=max(1, len(self) // 10))

    # -- query -------------------------------------------------------------

    def candidates(self, sketch: np.ndarray) -> set[int]:
        """LIVE row indices sharing >= 1 band signature with ``sketch``."""
        sketch = np.asarray(sketch, dtype=np.uint32)
        sigs = self._all_sigs(sketch[None, :])[0]
        out: set[int] = set()
        for band in range(self._total_bands):
            target = sigs[band]
            merged_s, merged_r = self._merged[band]
            lo = np.searchsorted(merged_s, target, side="left")
            hi = np.searchsorted(merged_s, target, side="right")
            if hi > lo:
                out.update(merged_r[lo:hi].tolist())
            n_p = self._pend_n[band]
            if n_p:
                hits = np.flatnonzero(self._pend_sigs[band][:n_p] == target)
                if hits.size:
                    out.update(self._pend_rows[band][hits].tolist())
        return {i for i in out if self._alive[i]}

    def query(
        self, sketch: np.ndarray, k: int = 10, min_jaccard: float = 0.0
    ) -> list[tuple[Hashable, float]]:
        cand = sorted(self.candidates(sketch))
        if not cand:
            return []
        scores = _score(
            np.asarray(sketch, dtype=np.uint32), self._mat[cand]
        )
        order = np.argsort(-scores)[:k]
        return [
            (self._keys[cand[i]], float(scores[i]))
            for i in order
            if scores[i] >= min_jaccard
        ]

    def query_brute(
        self, sketch: np.ndarray, k: int = 10
    ) -> list[tuple[Hashable, float]]:
        """Top-k over every live row (oracle path; one [N, K] device op
        for large corpora)."""
        if not len(self):
            return []
        query = np.asarray(sketch, dtype=np.uint32)
        if len(self) >= _SCORE_DEVICE_MIN:
            if self._dev is None or self._dev_gen != self._gen:
                self._dev_live = np.flatnonzero(self._alive[: self._n])
                self._dev = jnp.asarray(
                    _pad_pow2_rows(self._mat[self._dev_live])
                )
                self._dev_gen = self._gen
            live = self._dev_live
            kk = min(k, len(live))
            top_v, top_i = _topk_kernel(
                jnp.asarray(query), self._dev, len(live), kk
            )
            return [
                (self._keys[live[i]], float(v))
                for i, v in zip(np.asarray(top_i), np.asarray(top_v))
            ]
        live = np.flatnonzero(self._alive[: self._n])
        scores = _score(query, self._mat[live])
        order = np.argsort(-scores)[:k]
        return [(self._keys[live[i]], float(scores[i])) for i in order]

"""TPU compute plane: batched SHA-256, FastCDC chunking, MinHash dedup.

These are the ops behind the north-star metrics (BASELINE.json): the
``PieceHasher`` hot loops, content-defined chunking, and the near-duplicate
index. Pure JAX / Pallas; no service code lives here.
"""


def next_pow2(x: int) -> int:
    """Smallest power of two >= x (>= 1). The shape-bucketing primitive:
    jit caches stay bounded because every dynamic extent is rounded up."""
    return 1 << max(0, (x - 1).bit_length())

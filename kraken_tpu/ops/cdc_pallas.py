"""Pallas TPU kernel for the FastCDC gear pass: VMEM-resident doubling.

The XLA evaluation of the windowed gear sum (ops/cdc.py
``_gear_candidates``) round-trips every doubling step through HBM --
~40 B of HBM traffic per input byte -- capping it at ~10 GB/s/chip. This
kernel keeps all five doubling steps in VMEM and measured
**~43 GB/s/chip** with the robust chained method (44-62 with the
jitter-exposed marginal method; either way ~4-5x the XLA path --
PERF.md), bit-identical output.

Layout: bytes ride as [rows, 128] lane tiles in flat row-major order, so
a flat shift by ``step < 128`` is a lane-concat of each row's head with
the previous row's tail -- two vector selects, no relayout through HBM.
Each grid step processes one ``_SEG``-byte segment whose first ``_LEAD``
lanes carry the previous segment's last 31 bytes (same overlap scheme as
the XLA path, so candidates are bit-identical to a whole-blob pass).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kraken_tpu.ops.cdc import _WINDOW, _gear_fn_vec

_SEG = 1 << 18          # data bytes per grid step (VMEM-bounded: u32
                        # intermediates are 4x, plus live doubling copies)
_LEAD = 1024            # lane-aligned left-overlap region (last 31 used)
_BUF = _SEG + _LEAD
_ROWS = _BUF // 128
_PAD = _WINDOW - 1
_T_DISPATCH = 256       # segments per pallas_call (64 MiB data, 1 jit
                        # entry; large groups amortize per-call overhead)


def _make_kernel(mask_s: int, mask_l: int, first_group: bool):
    def kernel(d_ref, s_ref, l_ref):
        g = _gear_fn_vec(d_ref[0].astype(jnp.uint32))  # [_ROWS, 128]
        # Padding lanes must contribute ZERO history in g-domain --
        # gear(0) != 0, so zero BYTES are not enough (the XLA path pads
        # with uint32 zeros after the gear map; matching it exactly is
        # the bit-identity contract). Real history in the lead region is
        # only its last 31 lanes -- and none at all in the blob's first
        # segment.
        flat = (
            jax.lax.broadcasted_iota(jnp.int32, (_ROWS, 128), 0) * 128
            + jax.lax.broadcasted_iota(jnp.int32, (_ROWS, 128), 1)
        )
        cut = jnp.where(
            (pl.program_id(0) == 0) if first_group else False,
            _LEAD, _LEAD - _PAD,
        )
        g = jnp.where(flat < cut, jnp.uint32(0), g)
        h = g
        step = 1
        while step < _WINDOW:
            prev = jnp.concatenate(
                [jnp.zeros((1, 128), jnp.uint32), h[:-1]], axis=0
            )
            shifted = jnp.concatenate(
                [prev[:, 128 - step:], h[:, : 128 - step]], axis=1
            )
            h = h + (shifted << np.uint32(step))
            step *= 2
        hv = h[_LEAD // 128 :]
        s_ref[0] = ((hv & np.uint32(mask_s)) == 0).astype(jnp.uint8)
        l_ref[0] = ((hv & np.uint32(mask_l)) == 0).astype(jnp.uint8)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("mask_s", "mask_l", "first_group", "interpret"),
)
def _gear_pallas(
    segs_u8, mask_s: int, mask_l: int,
    first_group: bool = False, interpret: bool = False,
):
    """segs_u8: [T, _ROWS, 128] uint8 -> (strict, loose) [T, _SEG/128, 128]
    uint8 masks. ``first_group``: this dispatch's segment 0 is the BLOB's
    first segment (its whole lead region is padding, not overlap)."""
    t = segs_u8.shape[0]
    return pl.pallas_call(
        _make_kernel(mask_s, mask_l, first_group),
        interpret=interpret,
        grid=(t,),
        in_specs=[
            pl.BlockSpec(
                (1, _ROWS, 128), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=[
            pl.BlockSpec(
                (1, _SEG // 128, 128), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, _SEG // 128, 128), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, _SEG // 128, 128), jnp.uint8),
            jax.ShapeDtypeStruct((t, _SEG // 128, 128), jnp.uint8),
        ],
    )(segs_u8)


def candidate_indices_pallas(
    arr: np.ndarray, n: int, mask_s: int, mask_l: int,
    interpret: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Global strict/loose candidate positions over ``arr[:n]`` via the
    kernel. Drop-in for the XLA path's contract (zero history before
    offset 0; only positions < n returned)."""
    nseg = (n + _SEG - 1) // _SEG
    strict_parts: list[np.ndarray] = []
    loose_parts: list[np.ndarray] = []
    for group in range(0, nseg, _T_DISPATCH):
        t = min(_T_DISPATCH, nseg - group)
        # Dispatch size buckets to powers of two (bounded jit cache, same
        # trick as cdc.py's small-blob path): a 5 MiB blob must not pay a
        # fixed 64 MiB staging + transfer + fetch-back round.
        t_disp = 16
        while t_disp < t:
            t_disp *= 2
        segs = np.zeros((t_disp, _BUF), dtype=np.uint8)
        for i in range(t):
            s = (group + i) * _SEG
            lo = max(0, s - _PAD)
            chunk = arr[lo : min(s + _SEG, n)]
            segs[i, _LEAD - (s - lo) : _LEAD - (s - lo) + len(chunk)] = chunk
        strict, loose = _gear_pallas(
            jnp.asarray(segs.reshape(t_disp, _ROWS, 128)),
            mask_s, mask_l,
            first_group=(group == 0), interpret=interpret,
        )
        # Slice to live segments ON DEVICE: fetching the padded rows back
        # would double the D2H bytes for ragged tails.
        strict = np.asarray(strict[:t]).reshape(t, _SEG)
        loose = np.asarray(loose[:t]).reshape(t, _SEG)
        for i in range(t):
            s = (group + i) * _SEG
            valid = min(_SEG, n - s)
            strict_parts.append(np.flatnonzero(strict[i, :valid]) + s)
            loose_parts.append(np.flatnonzero(loose[i, :valid]) + s)
    return np.concatenate(strict_parts), np.concatenate(loose_parts)

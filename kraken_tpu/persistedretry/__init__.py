"""Durable async task queue with retry/backoff, surviving restarts.

Mirrors uber/kraken ``lib/persistedretry`` (tasks persisted locally;
executors retry with backoff until success; writeback and tag-replication
ride on it so crashes never lose work) -- upstream path, unverified;
SURVEY.md SS2.3/SS5. Persistence is stdlib sqlite3.
"""

from kraken_tpu.persistedretry.manager import Manager, Task, TaskStore

__all__ = ["Manager", "Task", "TaskStore"]

"""sqlite-backed durable retry queue."""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import sqlite3
import time
from typing import Awaitable, Callable, Optional

from kraken_tpu.utils.backoff import Backoff

_log = logging.getLogger("kraken.persistedretry")


@dataclasses.dataclass
class Task:
    """One durable unit of work. ``kind`` routes to an executor; ``payload``
    is executor-defined JSON. ``key`` dedups (same-key add is a no-op while
    the task is pending)."""

    kind: str
    key: str
    payload: dict
    attempts: int = 0
    not_before: float = 0.0
    id: Optional[int] = None


class TaskStore:
    """Persistence layer. One table, tiny schema, crash-safe."""

    def __init__(self, path: str):
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._db = sqlite3.connect(path)
        # WAL + synchronous=NORMAL: commits survive process crash always
        # and power loss up to the last WAL checkpoint sync -- the right
        # durability/cost point for a retry queue (a lost row re-enqueues
        # on the next trigger; a corrupt rollback journal would not).
        # ":memory:" (tests) doesn't support WAL; it reports its mode.
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS tasks (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                kind TEXT NOT NULL,
                key TEXT NOT NULL,
                payload TEXT NOT NULL,
                attempts INTEGER NOT NULL DEFAULT 0,
                not_before REAL NOT NULL DEFAULT 0,
                UNIQUE(kind, key)
            )"""
        )
        self._db.commit()

    def add(self, task: Task) -> bool:
        """Insert; returns False if a pending task with the same (kind, key)
        already exists."""
        try:
            cur = self._db.execute(
                "INSERT INTO tasks (kind, key, payload, attempts, not_before)"
                " VALUES (?, ?, ?, ?, ?)",
                (task.kind, task.key, json.dumps(task.payload), task.attempts,
                 task.not_before),
            )
            self._db.commit()
            task.id = cur.lastrowid
            return True
        except sqlite3.IntegrityError:
            return False

    def add_many(self, tasks: list[Task]) -> int:
        """Bulk insert in ONE transaction (one fsync, not len(tasks));
        existing (kind, key) rows are skipped. Returns rows inserted.
        Bulk enqueuers (the repair path) would otherwise stall the caller
        on a commit per task."""
        before = self._db.total_changes
        self._db.executemany(
            "INSERT OR IGNORE INTO tasks"
            " (kind, key, payload, attempts, not_before)"
            " VALUES (?, ?, ?, ?, ?)",
            [
                (t.kind, t.key, json.dumps(t.payload), t.attempts, t.not_before)
                for t in tasks
            ],
        )
        self._db.commit()
        return self._db.total_changes - before

    def ready(self, now: float, limit: int = 100) -> list[Task]:
        rows = self._db.execute(
            "SELECT id, kind, key, payload, attempts, not_before FROM tasks"
            " WHERE not_before <= ? ORDER BY id LIMIT ?",
            (now, limit),
        ).fetchall()
        return [
            Task(kind=k, key=key, payload=json.loads(p), attempts=a,
                 not_before=nb, id=i)
            for i, k, key, p, a, nb in rows
        ]

    def all_pending(self) -> list[Task]:
        return self.ready(now=float("inf"), limit=1_000_000)

    def count_pending(self, kind: str, key_prefix: str = "") -> int:
        """Pending tasks of ``kind`` whose key starts with ``key_prefix``
        (the replication unpin logic asks "any other task for this blob?")."""
        row = self._db.execute(
            "SELECT COUNT(*) FROM tasks WHERE kind = ? AND key GLOB ?",
            (kind, key_prefix.replace("*", "[*]") + "*"),
        ).fetchone()
        return int(row[0])

    def count_by_kind(self) -> dict[str, int]:
        """Pending tasks per kind, one aggregate scan -- the sentinel's
        queue-depth sample (a wedged executor shows up here as one kind
        growing without bound while the others drain)."""
        rows = self._db.execute(
            "SELECT kind, COUNT(*) FROM tasks GROUP BY kind"
        ).fetchall()
        return {kind: int(n) for kind, n in rows}

    def canonicalize_keys(self, kind: str, canonical: Callable[[dict], str]) -> int:
        """Rewrite pending keys of ``kind`` to ``canonical(payload)``.

        Key formats have changed across builds (digest-first reordering);
        tasks persisted by an older build still execute correctly from
        their payload but are invisible to the ``count_pending`` prefix
        scans the unpin logic relies on -- which can release an eviction
        pin while a legacy-keyed task for the same blob is still queued.
        Executors call this once at registration with their canonical key
        derivation. A legacy row whose canonical key already exists is a
        duplicate of the pending canonical task and is dropped. Returns
        rows migrated (rewritten + dropped)."""
        rows = self._db.execute(
            "SELECT id, key, payload FROM tasks WHERE kind = ?", (kind,)
        ).fetchall()
        changed = 0
        for row_id, key, payload in rows:
            want = canonical(json.loads(payload))
            if key == want:
                continue
            try:
                self._db.execute(
                    "UPDATE tasks SET key = ? WHERE id = ?", (want, row_id)
                )
            except sqlite3.IntegrityError:
                self._db.execute("DELETE FROM tasks WHERE id = ?", (row_id,))
            changed += 1
        if changed:
            self._db.commit()
        return changed

    def done(self, task: Task) -> None:
        self._db.execute("DELETE FROM tasks WHERE id = ?", (task.id,))
        self._db.commit()

    def reschedule(self, task: Task, not_before: float) -> None:
        self._db.execute(
            "UPDATE tasks SET attempts = ?, not_before = ? WHERE id = ?",
            (task.attempts, not_before, task.id),
        )
        self._db.commit()

    def close(self) -> None:
        self._db.close()


class Manager:
    """Polls the store and runs tasks through registered executors.

    ``register(kind, fn)`` with ``fn(task) -> Awaitable[None]``; a raise
    reschedules with exponential backoff. Call ``run_once()`` from tests or
    ``start()`` for the background loop.
    """

    def __init__(
        self,
        store: TaskStore,
        poll_interval_seconds: float = 1.0,
        backoff: Backoff | None = None,
        max_attempts: int = 0,  # 0 = retry forever (reference semantics)
        task_timeout_seconds: float = 1800.0,  # 0 = no per-task timeout
    ):
        self.store = store
        self.poll_interval = poll_interval_seconds
        self.backoff = backoff or Backoff(base_seconds=1.0, max_seconds=300.0)
        self.max_attempts = max_attempts
        # One poll loop serves EVERY task kind, so a single hung executor
        # (a writeback upload wedged on a dead backend socket) would
        # stall writeback, replication, AND heal forever. The timeout is
        # generous -- a multi-GiB writeback legitimately takes minutes --
        # but it must exist: a timed-out task just reschedules with
        # backoff like any other failure.
        self.task_timeout = task_timeout_seconds
        self._executors: dict[str, Callable[[Task], Awaitable[None]]] = {}
        self._task: Optional[asyncio.Task] = None
        self._poll_failures = None  # lazy FailureMeter (start() only)

    def register(self, kind: str, fn: Callable[[Task], Awaitable[None]]) -> None:
        self._executors[kind] = fn

    def add(self, task: Task) -> bool:
        return self.store.add(task)

    def add_many(self, tasks: list[Task]) -> int:
        return self.store.add_many(tasks)

    def queue_depths(self) -> dict[str, int]:
        """Pending depth per kind, REGISTERED kinds always present (a
        healthy empty queue reports 0, not absence -- the sentinel's
        gauge must not drop a label the moment a queue drains)."""
        depths = {kind: 0 for kind in self._executors}
        depths.update(self.store.count_by_kind())
        return depths

    async def run_once(self, now: float | None = None) -> int:
        """One poll cycle; returns number of tasks that succeeded."""
        now = time.time() if now is None else now
        ok = 0
        for task in self.store.ready(now):
            fn = self._executors.get(task.kind)
            if fn is None:
                continue  # executor not registered (yet); leave queued
            try:
                if self.task_timeout > 0:
                    try:
                        await asyncio.wait_for(fn(task), self.task_timeout)
                    except asyncio.TimeoutError:
                        from kraken_tpu.utils.metrics import REGISTRY

                        REGISTRY.counter(
                            "retry_task_timeouts_total",
                            "Retry tasks cancelled at task_timeout_seconds",
                        ).inc(kind=task.kind)
                        _log.warning(
                            "retry task timed out; rescheduling",
                            extra={
                                "kind": task.kind, "key": task.key,
                                "timeout_seconds": self.task_timeout,
                            },
                        )
                        raise
                else:
                    await fn(task)
            except Exception:
                task.attempts += 1
                if self.max_attempts and task.attempts >= self.max_attempts:
                    self.store.done(task)
                else:
                    self.store.reschedule(
                        task, now + self.backoff.delay(task.attempts - 1)
                    )
            else:
                self.store.done(task)
                ok += 1
        return ok

    def start(self) -> None:
        # The poll itself can raise (transient sqlite disk error in
        # store.ready, or done/reschedule mid-cycle). An unguarded loop
        # dies SILENTLY on the first such error -- every durable plane
        # (writeback, replication, heal) then stops forever while the
        # process looks healthy. Meter + structured WARN + keep polling.
        from kraken_tpu.utils.metrics import FailureMeter

        if self._poll_failures is None:
            self._poll_failures = FailureMeter(
                "retry_poll_errors_total",
                "Retry-queue poll cycles that raised (loop kept polling)",
                _log,
            )

        async def loop():
            while True:
                try:
                    await self.run_once()
                except Exception as e:
                    self._poll_failures.record("retry poll", e)
                await asyncio.sleep(self.poll_interval)

        self._task = asyncio.create_task(loop())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def reap(self) -> None:
        """Await the cancelled poll task (after :meth:`stop`, before
        :meth:`close`). cancel() only SCHEDULES the CancelledError --
        it lands at the task's next await -- so closing the sqlite
        store while run_once is still in flight turns shutdown into
        "Cannot operate on a closed database" poll noise and strands
        the task past the test body (the asyncio-task tripwire and the
        `fire-and-forget-task` lint rule exist for exactly this class).
        Idempotent; cancels too if stop() was skipped."""
        if self._task is None:
            return
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        except Exception:
            _log.debug("retry poll task raised at shutdown", exc_info=True)
        self._task = None

    def close(self) -> None:
        """Release the task store's sqlite handle. Call AFTER stop()
        and after the node's listeners are down: a request handler
        mid-commit may still enqueue until then, and the poll task's
        cancellation lands at its next await -- neither touches the DB
        afterwards (it lives on the loop thread). Without this, every
        node start/stop cycle strands one sqlite fd -- the exact slow
        EMFILE class the resource sentinel + soak harness exist to
        catch (and did)."""
        self.store.close()

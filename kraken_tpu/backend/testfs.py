"""testfs: a trivial HTTP file server + client -- the universal fake backend.

Mirrors uber/kraken ``lib/backend/testfs`` (HTTP file server standing in
for S3/GCS/... in every integration test) -- upstream path, unverified;
SURVEY.md SS2.3/SS4. The server half runs in the herd; the client half
registers as backend ``testfs``.
"""

from __future__ import annotations

from aiohttp import web

from kraken_tpu.backend.base import (
    BackendClient,
    BlobInfo,
    BlobNotFoundError,
    register_backend,
)
from kraken_tpu.utils.httputil import HTTPClient, HTTPError, base_url


@register_backend("testfs")
class TestFSClient(BackendClient):
    def __init__(self, config: dict):
        self.addr = config["addr"]  # host:port
        self._http = HTTPClient(retries=config.get("retries", 3))

    def _url(self, name: str) -> str:
        return f"{base_url(self.addr)}/files/{name}"

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        try:
            body = await self._http.get(self._url(name) + "?stat=1")
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        return BlobInfo(int(body))

    async def download(self, namespace: str, name: str) -> bytes:
        try:
            return await self._http.get(self._url(name))
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        await self._http.put(self._url(name), data=data)

    async def list(self, prefix: str) -> list[str]:
        body = await self._http.get(f"{base_url(self.addr)}/list/{prefix}")
        return [l for l in body.decode().splitlines() if l]

    async def close(self) -> None:
        await self._http.close()


class TestFSServer:
    """In-memory HTTP file server. ``async with TestFSServer(port) as s:``"""

    __test__ = False  # not a pytest class despite the name

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self.host = host
        self.port = port
        self._files: dict[str, bytes] = {}
        self._runner: web.AppRunner | None = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 30)
        app.router.add_get("/files/{name:.*}", self._get)
        app.router.add_put("/files/{name:.*}", self._put)
        app.router.add_get("/list/{prefix:.*}", self._list)
        return app

    async def _get(self, req: web.Request) -> web.Response:
        name = req.match_info["name"]
        data = self._files.get(name)
        if data is None:
            return web.Response(status=404)
        if req.query.get("stat"):
            return web.Response(text=str(len(data)))
        return web.Response(body=data)

    async def _put(self, req: web.Request) -> web.Response:
        self._files[req.match_info["name"]] = await req.read()
        return web.Response(status=201)

    async def _list(self, req: web.Request) -> web.Response:
        prefix = req.match_info["prefix"]
        names = sorted(n for n in self._files if n.startswith(prefix))
        return web.Response(text="\n".join(names))

    async def __aenter__(self) -> "TestFSServer":
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def __aexit__(self, *exc) -> None:
        if self._runner:
            await self._runner.cleanup()

"""Backend interface, registry, and the namespace->client manager."""

from __future__ import annotations

import asyncio
import os
import re
from typing import Callable, Dict, Optional

from kraken_tpu.utils.bandwidth import TokenBucket


class BackendError(Exception):
    pass


class BlobNotFoundError(BackendError):
    """Named blob absent in the backend."""


class BlobInfo:
    __slots__ = ("size",)

    def __init__(self, size: int):
        self.size = size


class BackendClient:
    """Async client for one remote store.

    Names are backend-relative paths (the pather in
    :mod:`kraken_tpu.backend.namepath` maps digests/tags to them).
    """

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        raise NotImplementedError

    async def download(self, namespace: str, name: str) -> bytes:
        raise NotImplementedError

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        raise NotImplementedError

    async def upload_file(self, namespace: str, name: str, path: str) -> None:
        """Upload from a local file. Default: buffer + :meth:`upload`
        (correct for all backends; memory-bound for multi-GB blobs).
        Backends with a streaming/multipart story override this -- the
        writeback plane always calls THIS, so overriding is sufficient."""

        def _read() -> bytes:
            with open(path, "rb") as f:
                return f.read()

        data = await asyncio.to_thread(_read)
        await self.upload(namespace, name, data)

    async def download_to_file(
        self, namespace: str, name: str, dest_path: str
    ) -> int:
        """Download into a local file; returns byte count. Default:
        :meth:`download` + write (memory-bound); streaming backends
        override."""
        data = await self.download(namespace, name)

        def _write() -> None:
            with open(dest_path, "wb") as f:
                f.write(data)

        await asyncio.to_thread(_write)
        return len(data)

    async def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    async def close(self) -> None:
        pass


_REGISTRY: Dict[str, Callable[[dict], BackendClient]] = {}


def register_backend(name: str):
    """Decorator: register a backend factory under ``name`` (the YAML
    ``backend:`` key, same plugin pattern as the hasher registry)."""

    def deco(factory: Callable[[dict], BackendClient]):
        _REGISTRY[name] = factory
        return factory

    return deco


def make_backend(name: str, config: dict | None = None) -> BackendClient:
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown backend {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None
    return factory(config or {})


class _ThrottledClient(BackendClient):
    """Wraps a client with ingress/egress token buckets (bytes/sec)."""

    def __init__(self, inner: BackendClient, ingress_bps: float, egress_bps: float):
        self._inner = inner
        self._ingress = TokenBucket(ingress_bps)
        self._egress = TokenBucket(egress_bps)

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        return await self._inner.stat(namespace, name)

    async def download(self, namespace: str, name: str) -> bytes:
        data = await self._inner.download(namespace, name)
        await self._ingress.acquire(len(data))
        return data

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        await self._egress.acquire(len(data))
        await self._inner.upload(namespace, name, data)

    async def upload_file(self, namespace: str, name: str, path: str) -> None:
        size = await asyncio.to_thread(os.path.getsize, path)
        await self._egress.acquire(size)
        await self._inner.upload_file(namespace, name, path)

    async def download_to_file(
        self, namespace: str, name: str, dest_path: str
    ) -> int:
        n = await self._inner.download_to_file(namespace, name, dest_path)
        await self._ingress.acquire(n)
        return n

    async def list(self, prefix: str) -> list[str]:
        return await self._inner.list(prefix)

    async def close(self) -> None:
        await self._inner.close()


class Manager:
    """Resolves a namespace to its backend client.

    Config shape (YAML-mirrored):

        backends:
          - namespace: "library/.*"
            backend: testfs
            config: {addr: "localhost:9000"}
            bandwidth: {ingress_bps: 0, egress_bps: 0}

    First matching entry wins, as in the reference.
    """

    def __init__(self, entries: list[dict] | None = None):
        self._entries: list[tuple[re.Pattern, BackendClient]] = []
        for e in entries or []:
            client = make_backend(e["backend"], e.get("config"))
            bw = e.get("bandwidth") or {}
            if bw.get("ingress_bps") or bw.get("egress_bps"):
                client = _ThrottledClient(
                    client, bw.get("ingress_bps", 0), bw.get("egress_bps", 0)
                )
            self.register(e["namespace"], client)

    def register(self, namespace_pattern: str, client: BackendClient) -> None:
        self._entries.append((re.compile(namespace_pattern + r"\Z"), client))

    def get_client(self, namespace: str) -> BackendClient:
        for pattern, client in self._entries:
            if pattern.match(namespace):
                return client
        raise KeyError(f"no backend configured for namespace {namespace!r}")

    def try_get_client(self, namespace: str) -> Optional[BackendClient]:
        try:
            return self.get_client(namespace)
        except KeyError:
            return None

    async def close(self) -> None:
        for _p, c in self._entries:
            await c.close()

"""Pull-through backend against an upstream Docker registry.

Mirrors uber/kraken ``lib/backend/registrybackend`` (blobs + tags clients
speaking the Registry v2 API to an existing registry, plus the
``security`` token-auth flow; how real clusters bootstrap content they
didn't push) -- upstream path, unverified; SURVEY.md SS2.3.

Two registrations:

- ``registry_blob``: name = blob digest (hex or ``sha256:<hex>``);
  download GETs ``/v2/{namespace}/blobs/sha256:<hex>``. Read-only.
- ``registry_tag``: name = ``repo:tag``; download resolves the manifest
  and returns the manifest DIGEST string (the tag value the build-index
  stores), taken from ``Docker-Content-Digest`` or hashed from the body.

Auth: real registries (Docker Hub, GHCR, Quay) answer anonymous requests
with ``401`` + ``WWW-Authenticate: Bearer realm=...,service=...`` and
expect the docker token flow: GET the realm (with basic credentials if
the account is private) for a short-lived JWT, then retry with
``Authorization: Bearer``. :class:`_AuthSession` implements that flow
with a per-scope token cache; plain ``Basic`` challenges are answered
directly. Configure ``username``/``password`` for private upstreams;
public pulls work anonymously (the token endpoint still issues a token).
"""

from __future__ import annotations

import base64
import hashlib
import json
import re
import time
from urllib.parse import urlencode, urljoin

from kraken_tpu.backend.base import (
    BackendClient,
    BackendError,
    BlobInfo,
    BlobNotFoundError,
    register_backend,
)
from kraken_tpu.utils.httputil import HTTPClient, HTTPError

_MANIFEST_ACCEPT = ", ".join(
    (
        "application/vnd.docker.distribution.manifest.v2+json",
        "application/vnd.docker.distribution.manifest.list.v2+json",
        "application/vnd.oci.image.manifest.v1+json",
        "application/vnd.oci.image.index.v1+json",
    )
)

_CHALLENGE_PARAM = re.compile(r'(\w+)="([^"]*)"')


def _realm_safe_for_credentials(realm: str) -> bool:
    """https always; plain http only to loopback (dev/test rigs)."""
    from urllib.parse import urlsplit

    try:
        parts = urlsplit(realm)
    except ValueError:  # e.g. unbalanced IPv6 bracket -- treat as unsafe
        return False
    if parts.scheme == "https":
        return True
    if parts.scheme != "http":
        return False
    return parts.hostname in ("127.0.0.1", "localhost", "::1")


def _full_digest(name: str) -> str:
    return name if name.startswith("sha256:") else f"sha256:{name}"


class _AuthSession:
    """Docker registry token auth with a per-scope cache.

    One instance per backend client. Tokens are cached until shortly
    before their advertised expiry (a 10 s guard band keeps a token from
    dying between the cache check and the upstream's clock).
    """

    def __init__(self, http: HTTPClient, username: str = "", password: str = ""):
        self._http = http
        self._username = username
        self._password = password
        self._tokens: dict[str, tuple[str, float]] = {}  # scope -> (tok, exp)

    async def request(
        self,
        method: str,
        url: str,
        *,
        scope: str,
        headers: dict | None = None,
        ok: tuple[int, ...] = (200,),
        retry_5xx: bool = True,
    ) -> tuple[int, dict, bytes]:
        hdrs = dict(headers or {})
        cached = self._cached(scope)
        if cached:
            hdrs["Authorization"] = cached
        status, h, b = await self._one_hop(
            method, url, hdrs, ok=tuple(ok) + (401,), retry_5xx=retry_5xx
        )
        if status != 401:
            return status, h, b
        hdrs["Authorization"] = await self._answer(
            h.get("WWW-Authenticate", ""), scope
        )
        return await self._one_hop(
            method, url, hdrs, ok=tuple(ok), retry_5xx=retry_5xx
        )

    async def _one_hop(
        self, method: str, url: str, hdrs: dict, *, ok, retry_5xx
    ) -> tuple[int, dict, bytes]:
        """One request, following redirects MANUALLY so the registry
        Authorization header is dropped on the redirected hop: real
        upstreams answer authorized blob GETs with 307 to a presigned
        S3/CDN URL, and S3 rejects requests carrying BOTH presigned
        query auth and an Authorization header."""
        redirects = (301, 302, 303, 307, 308)
        status, h, b = await self._http.request_full(
            method, url, headers=hdrs, ok_statuses=tuple(ok) + redirects,
            retry_5xx=retry_5xx, allow_redirects=False,
        )
        for _hop in range(5):  # kt-lint: disable=retry-without-deadline  # bounded 5-hop redirect follow, not a retry sweep; each hop is one HTTPClient request with its own timeout+retry budget
            if status not in redirects:
                return status, h, b
            location = h.get("Location", "")
            if not location:
                raise HTTPError(method, url, status, b"redirect without Location")
            url = urljoin(url, location)
            clean = {k: v for k, v in hdrs.items() if k != "Authorization"}
            status, h, b = await self._http.request_full(
                method, url, headers=clean,
                ok_statuses=tuple(ok) + redirects,
                retry_5xx=retry_5xx, allow_redirects=False,
            )
        raise HTTPError(method, url, status, b"too many redirects")

    def _cached(self, scope: str) -> str | None:
        tok = self._tokens.get(scope)
        if tok and tok[1] > time.monotonic():
            return tok[0]
        return None

    def _basic(self) -> str:
        creds = f"{self._username}:{self._password}".encode()
        return "Basic " + base64.b64encode(creds).decode()

    async def _answer(self, challenge: str, scope: str) -> str:
        scheme, _, rest = challenge.partition(" ")
        scheme = scheme.lower()
        if scheme == "basic":
            if not self._username:
                raise BackendError(
                    "upstream registry requires basic auth; configure "
                    "username/password on the backend"
                )
            value = self._basic()
            # Cache under the CALLER's scope (the lookup key) so every
            # subsequent request attaches it proactively instead of
            # eating a guaranteed 401 + retry round-trip.
            self._tokens[scope] = (value, float("inf"))
            return value
        if scheme != "bearer":
            raise BackendError(
                f"unsupported upstream auth challenge: {challenge!r}"
            )
        params = dict(_CHALLENGE_PARAM.findall(rest))
        realm = params.get("realm")
        if not realm:
            raise BackendError(f"bearer challenge without realm: {challenge!r}")
        if self._username and not _realm_safe_for_credentials(realm):
            # The 401 challenge names the token realm; a spoofed or
            # misconfigured plain-http realm would receive our Basic
            # credentials in cleartext. Never send secrets over the
            # network unencrypted (loopback realms are fine: dev rigs).
            raise BackendError(
                f"refusing to send credentials to non-https token realm "
                f"{realm!r} (upstream challenge may be spoofed)"
            )
        # The challenge's own scope wins (the upstream knows what it wants
        # granted); the caller's is the fallback for terse challenges.
        use_scope = params.get("scope") or scope
        query = {
            k: v
            for k, v in (
                ("service", params.get("service", "")),
                ("scope", use_scope),
            )
            if v
        }
        token_url = realm + (f"?{urlencode(query)}" if query else "")
        token_headers = (
            {"Authorization": self._basic()} if self._username else None
        )
        try:
            body = await self._http.get(token_url, headers=token_headers)
        except HTTPError as e:
            raise BackendError(
                f"token endpoint refused ({e.status}): check credentials"
            ) from e
        try:
            payload = json.loads(body)
        except ValueError:
            raise BackendError("token endpoint returned non-JSON") from None
        tok = payload.get("token") or payload.get("access_token")
        if not isinstance(tok, str) or not tok:
            raise BackendError("token endpoint returned no token")
        ttl = float(payload.get("expires_in") or 60.0)
        value = f"Bearer {tok}"
        entry = (value, time.monotonic() + max(ttl - 10.0, 10.0))
        # Store under the CALLER's scope too: lookups key on it, and an
        # upstream whose challenge carries a broader/re-normalized scope
        # string would otherwise never hit the cache (three round-trips
        # per request, hammering a rate-limited token endpoint).
        self._tokens[use_scope] = entry
        self._tokens[scope] = entry
        return value


class _RegistryBase(BackendClient):
    def __init__(self, config: dict):
        addr = config["address"]
        scheme = "https" if config.get("tls", False) else "http"
        self.base = f"{scheme}://{addr}/v2"
        self._http = HTTPClient(retries=config.get("retries", 3))
        self._auth = _AuthSession(
            self._http,
            username=config.get("username", ""),
            password=config.get("password", ""),
        )

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        raise BackendError("registry backend is read-only (pull-through)")

    async def list(self, prefix: str) -> list[str]:
        raise BackendError("registry backend does not support list")

    async def close(self) -> None:
        await self._http.close()


@register_backend("registry_blob")
class RegistryBlobBackend(_RegistryBase):
    """config: address ("host:port"), tls (false), retries, username,
    password (empty = anonymous token flow)."""

    def _url(self, namespace: str, name: str) -> str:
        return f"{self.base}/{namespace}/blobs/{_full_digest(name)}"

    @staticmethod
    def _scope(namespace: str) -> str:
        return f"repository:{namespace}:pull"

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        try:
            _s, headers, _b = await self._auth.request(
                "HEAD", self._url(namespace, name),
                scope=self._scope(namespace), retry_5xx=False,
            )
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        return BlobInfo(int(headers.get("Content-Length", 0)))

    async def download(self, namespace: str, name: str) -> bytes:
        try:
            _s, _h, body = await self._auth.request(
                "GET", self._url(namespace, name),
                scope=self._scope(namespace),
            )
            return body
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise


@register_backend("registry_tag")
class RegistryTagBackend(_RegistryBase):
    """Resolves ``repo:tag`` names to manifest digests via the upstream."""

    def _split(self, name: str) -> tuple[str, str]:
        repo, sep, tag = name.rpartition(":")
        if not sep:
            raise BackendError(f"tag name must be repo:tag, got {name!r}")
        return repo, tag

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        digest = await self.download(namespace, name)
        return BlobInfo(len(digest))

    async def download(self, namespace: str, name: str) -> bytes:
        repo, tag = self._split(name)
        try:
            _s, headers, body = await self._auth.request(
                "GET", f"{self.base}/{repo}/manifests/{tag}",
                scope=f"repository:{repo}:pull",
                headers={"Accept": _MANIFEST_ACCEPT},
            )
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        digest = headers.get("Docker-Content-Digest")
        if not digest:
            digest = "sha256:" + hashlib.sha256(body).hexdigest()
        return digest.encode()

"""Pull-through backend against an upstream Docker registry.

Mirrors uber/kraken ``lib/backend/registrybackend`` (blobs + tags clients
speaking the Registry v2 API to an existing registry; how real clusters
bootstrap content they didn't push) -- upstream path, unverified; SURVEY.md
SS2.3.

Two registrations:

- ``registry_blob``: name = blob digest (hex or ``sha256:<hex>``);
  download GETs ``/v2/{namespace}/blobs/sha256:<hex>``. Read-only.
- ``registry_tag``: name = ``repo:tag``; download resolves the manifest
  and returns the manifest DIGEST string (the tag value the build-index
  stores), taken from ``Docker-Content-Digest`` or hashed from the body.
"""

from __future__ import annotations

import hashlib

from kraken_tpu.backend.base import (
    BackendClient,
    BackendError,
    BlobInfo,
    BlobNotFoundError,
    register_backend,
)
from kraken_tpu.utils.httputil import HTTPClient, HTTPError

_MANIFEST_ACCEPT = ", ".join(
    (
        "application/vnd.docker.distribution.manifest.v2+json",
        "application/vnd.docker.distribution.manifest.list.v2+json",
        "application/vnd.oci.image.manifest.v1+json",
        "application/vnd.oci.image.index.v1+json",
    )
)


def _full_digest(name: str) -> str:
    return name if name.startswith("sha256:") else f"sha256:{name}"


class _RegistryBase(BackendClient):
    def __init__(self, config: dict):
        addr = config["address"]
        scheme = "https" if config.get("tls", False) else "http"
        self.base = f"{scheme}://{addr}/v2"
        self._http = HTTPClient(retries=config.get("retries", 3))

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        raise BackendError("registry backend is read-only (pull-through)")

    async def list(self, prefix: str) -> list[str]:
        raise BackendError("registry backend does not support list")

    async def close(self) -> None:
        await self._http.close()


@register_backend("registry_blob")
class RegistryBlobBackend(_RegistryBase):
    """config: address ("host:port"), tls (false), retries."""

    def _url(self, namespace: str, name: str) -> str:
        return f"{self.base}/{namespace}/blobs/{_full_digest(name)}"

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        try:
            _s, headers, _b = await self._http.request_full(
                "HEAD", self._url(namespace, name), ok_statuses=(200,),
                retry_5xx=False,
            )
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        return BlobInfo(int(headers.get("Content-Length", 0)))

    async def download(self, namespace: str, name: str) -> bytes:
        try:
            return await self._http.get(self._url(namespace, name))
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise


@register_backend("registry_tag")
class RegistryTagBackend(_RegistryBase):
    """Resolves ``repo:tag`` names to manifest digests via the upstream."""

    def _url(self, name: str) -> str:
        repo, sep, tag = name.rpartition(":")
        if not sep:
            raise BackendError(f"tag name must be repo:tag, got {name!r}")
        return f"{self.base}/{repo}/manifests/{tag}"

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        digest = await self.download(namespace, name)
        return BlobInfo(len(digest))

    async def download(self, namespace: str, name: str) -> bytes:
        try:
            _s, headers, body = await self._http.request_full(
                "GET", self._url(name),
                headers={"Accept": _MANIFEST_ACCEPT}, ok_statuses=(200,),
            )
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        digest = headers.get("Docker-Content-Digest")
        if not digest:
            digest = "sha256:" + hashlib.sha256(body).hexdigest()
        return digest.encode()

"""Blob-name -> backend-path mapping policies.

Mirrors uber/kraken ``lib/backend/namepath`` (``identity``, ``docker_tag``,
``sharded_docker_blob``) -- upstream path, unverified; SURVEY.md SS2.3.
"""

from __future__ import annotations

_PATHERS = {}


def register_pather(name: str):
    def deco(fn):
        _PATHERS[name] = fn
        return fn

    return deco


def get_pather(name: str):
    return _PATHERS[name]


@register_pather("identity")
def identity(root: str, name: str) -> str:
    return f"{root}/{name}" if root else name


@register_pather("sharded_docker_blob")
def sharded_docker_blob(root: str, name: str) -> str:
    """``<root>/<hex[:2]>/<hex[2:4]>/<hex>`` -- spreads blobs across
    prefixes for object stores that shard by key prefix."""
    prefix = f"{root}/" if root else ""
    return f"{prefix}{name[:2]}/{name[2:4]}/{name}"


@register_pather("docker_tag")
def docker_tag(root: str, name: str) -> str:
    """``repo:tag`` -> ``<root>/<repo>/_manifests/tags/<tag>/current/link``."""
    repo, sep, tag = name.rpartition(":")
    if not sep:
        raise ValueError(f"tag name must be repo:tag, got {name!r}")
    prefix = f"{root}/" if root else ""
    return f"{prefix}{repo}/_manifests/tags/{tag}/current/link"

"""HDFS backend over the WebHDFS REST API.

Mirrors uber/kraken ``lib/backend/hdfsbackend`` (Stat/Download/Upload/List
against HDFS via webhdfs) -- upstream path, unverified; SURVEY.md SS2.3.
The two-step CREATE/OPEN redirect dance (namenode 307 -> datanode) is
followed manually so the data body is only sent to the datanode, exactly
as the protocol specifies.
"""

from __future__ import annotations

import json
import urllib.parse

from kraken_tpu.backend.base import (
    BackendClient,
    BlobInfo,
    BlobNotFoundError,
    register_backend,
)
from kraken_tpu.backend.namepath import get_pather
from kraken_tpu.utils.httputil import HTTPClient, HTTPError


@register_backend("hdfs")
class HDFSBackend(BackendClient):
    """config: namenode ("http://host:9870"), root, user ("kraken"),
    pather ("sharded_docker_blob")."""

    def __init__(self, config: dict):
        self.namenode = config["namenode"].rstrip("/")
        self.user = config.get("user", "kraken")
        self.root = config.get("root", "")
        self._pather = get_pather(config.get("pather", "sharded_docker_blob"))
        self._http = HTTPClient(retries=config.get("retries", 3))

    def _url(self, path: str, op: str, **params) -> str:
        q = {"op": op, "user.name": self.user, **params}
        return (
            f"{self.namenode}/webhdfs/v1/"
            + urllib.parse.quote(path)
            + "?"
            + urllib.parse.urlencode(q)
        )

    def _path(self, name: str) -> str:
        return self._pather(self.root, name)

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        try:
            body = await self._http.get(
                self._url(self._path(name), "GETFILESTATUS")
            )
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        return BlobInfo(json.loads(body)["FileStatus"]["length"])

    async def download(self, namespace: str, name: str) -> bytes:
        try:
            return await self._http.get(self._url(self._path(name), "OPEN"))
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        # Step 1: namenode returns 307 with the datanode Location.
        status, headers, _ = await self._http.request_full(
            "PUT",
            self._url(self._path(name), "CREATE", overwrite="true"),
            ok_statuses=(307,),
            allow_redirects=False,
        )
        # Step 2: send the bytes to the datanode.
        await self._http.request_full(
            "PUT", headers["Location"], data=data, ok_statuses=(200, 201)
        )

    async def list(self, prefix: str) -> list[str]:
        path = f"{self.root}/{prefix}" if self.root else prefix
        try:
            body = await self._http.get(self._url(path, "LISTSTATUS"))
        except HTTPError as e:
            if e.status == 404:
                return []
            raise
        statuses = json.loads(body)["FileStatuses"]["FileStatus"]
        return [s["pathSuffix"] for s in statuses]

    async def close(self) -> None:
        await self._http.close()

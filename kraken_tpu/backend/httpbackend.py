"""Read-only HTTP(S) backend: GET blobs from an arbitrary URL template.

Mirrors uber/kraken ``lib/backend/httpbackend`` (download-only backend
against plain HTTP endpoints) -- upstream path, unverified; SURVEY.md SS2.3.
"""

from __future__ import annotations

from kraken_tpu.backend.base import (
    BackendClient,
    BackendError,
    BlobInfo,
    BlobNotFoundError,
    register_backend,
)
from kraken_tpu.utils.httputil import HTTPClient, HTTPError


@register_backend("http")
class HTTPBackend(BackendClient):
    """config: ``{"download_url": "http://host/blobs/%s"}`` -- %s <- name."""

    def __init__(self, config: dict):
        self.download_url = config["download_url"]
        self._http = HTTPClient(retries=config.get("retries", 3))

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        data = await self.download(namespace, name)
        return BlobInfo(len(data))

    async def download(self, namespace: str, name: str) -> bytes:
        try:
            return await self._http.get(self.download_url % name)
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        raise BackendError("http backend is read-only")

    async def list(self, prefix: str) -> list[str]:
        raise BackendError("http backend does not support list")

    async def close(self) -> None:
        await self._http.close()

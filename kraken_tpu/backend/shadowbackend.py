"""Shadow backend: writes to two backends, reads from the primary.

Mirrors uber/kraken ``lib/backend/shadowbackend`` (migration aid: dual-write
while moving between stores) -- upstream path, unverified; SURVEY.md SS2.3.
"""

from __future__ import annotations

from kraken_tpu.backend.base import (
    BackendClient,
    BlobInfo,
    BlobNotFoundError,
    make_backend,
    register_backend,
)


@register_backend("shadow")
class ShadowBackend(BackendClient):
    """config: ``{"primary": {"backend": ..., "config": ...},
    "shadow": {...}}``."""

    def __init__(self, config: dict):
        p, s = config["primary"], config["shadow"]
        self._primary = make_backend(p["backend"], p.get("config"))
        self._shadow = make_backend(s["backend"], s.get("config"))

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        return await self._primary.stat(namespace, name)

    async def download(self, namespace: str, name: str) -> bytes:
        try:
            return await self._primary.download(namespace, name)
        except BlobNotFoundError:
            return await self._shadow.download(namespace, name)

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        await self._primary.upload(namespace, name, data)
        await self._shadow.upload(namespace, name, data)

    async def list(self, prefix: str) -> list[str]:
        return await self._primary.list(prefix)

    async def close(self) -> None:
        await self._primary.close()
        await self._shadow.close()

"""S3-compatible object-store backend (AWS Signature V4, pure stdlib).

Mirrors uber/kraken ``lib/backend/s3backend`` (Stat/Download/Upload/List
against S3) -- upstream path, unverified; SURVEY.md SS2.3 -- rebuilt over
the S3 REST API directly (no SDK in the image): SigV4 request signing with
hmac/hashlib, ListObjectsV2 XML via xml.etree. Works against AWS, MinIO,
and the in-repo fake (tests/test_cloud_backends.py).

The ``gcs`` registration reuses this client against Google Cloud
Storage's S3-interoperable XML API (HMAC keys;
https://storage.googleapis.com) -- a deliberate divergence from upstream's
native-SDK gcsbackend, chosen because the interop surface keeps one signed
client for both clouds.
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import logging
import os
import urllib.parse
import xml.etree.ElementTree as ET

from kraken_tpu.backend.base import (
    BackendClient,
    BlobInfo,
    BlobNotFoundError,
    register_backend,
)
from kraken_tpu.backend.namepath import get_pather
from kraken_tpu.utils.httputil import HTTPClient, HTTPError

_log = logging.getLogger("kraken.backend.s3")

_EMPTY_SHA = hashlib.sha256(b"").hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(
    method: str,
    url: str,
    *,
    access_key: str,
    secret_key: str,
    region: str,
    service: str = "s3",
    payload_sha256: str = _EMPTY_SHA,
    now: datetime.datetime | None = None,
) -> dict:
    """AWS Signature V4 headers for one request (host-style or path-style).

    Returns {"Authorization", "x-amz-date", "x-amz-content-sha256"}.
    """
    parts = urllib.parse.urlsplit(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    # The request path is already single-percent-encoded by the caller
    # (``_url`` quotes the key); S3 canonicalizes the path exactly as sent,
    # so re-quoting here would double-encode ('%' -> '%25') and produce
    # SignatureDoesNotMatch for any key containing ':', '+', space, etc.
    canonical_uri = parts.path or "/"
    # Query keys/values must be sorted and URI-encoded.
    q = urllib.parse.parse_qsl(parts.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(q)
    )
    host = parts.netloc
    canonical_headers = (
        f"host:{host}\nx-amz-content-sha256:{payload_sha256}\n"
        f"x-amz-date:{amz_date}\n"
    )
    signed = "host;x-amz-content-sha256;x-amz-date"
    creq = "\n".join(
        (method, canonical_uri, canonical_query, canonical_headers, signed,
         payload_sha256)
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    sts = "\n".join(
        ("AWS4-HMAC-SHA256", amz_date, scope,
         hashlib.sha256(creq.encode()).hexdigest())
    )
    k = _hmac(b"AWS4" + secret_key.encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    return {
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={signature}"
        ),
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_sha256,
    }


@register_backend("s3")
class S3Backend(BackendClient):
    """config: endpoint, bucket, access_key, secret_key, region ("us-east-1"),
    pather ("sharded_docker_blob"), root ("")."""

    service = "s3"

    def __init__(self, config: dict):
        self.endpoint = config["endpoint"].rstrip("/")
        self.bucket = config["bucket"]
        self.access_key = config.get("access_key", "")
        self.secret_key = config.get("secret_key", "")
        self.region = config.get("region", "us-east-1")
        self.root = config.get("root", "")
        self._pather = get_pather(config.get("pather", "sharded_docker_blob"))
        self._http = HTTPClient(retries=config.get("retries", 3))
        # Multipart knobs: S3's floor is 5 MiB/part; 64 MiB parts keep a
        # 5 GiB layer at ~80 requests while bounding memory to one part.
        self.multipart_threshold = int(
            config.get("multipart_threshold", 64 * 1024 * 1024)
        )
        self.multipart_part_size = max(
            int(config.get("multipart_part_size", 64 * 1024 * 1024)),
            5 * 1024 * 1024,
        )

    def _url(self, key: str) -> str:
        return f"{self.endpoint}/{self.bucket}/" + urllib.parse.quote(key)

    def _key(self, name: str) -> str:
        return self._pather(self.root, name)

    async def _signed(
        self, method: str, url: str, data: bytes | None = None,
        ok=(200, 201, 204),
    ):
        payload_sha = hashlib.sha256(data or b"").hexdigest()
        headers = sigv4_headers(
            method, url,
            access_key=self.access_key, secret_key=self.secret_key,
            region=self.region, service=self.service,
            payload_sha256=payload_sha,
        )
        return await self._http.request_full(
            method, url, data=data, headers=headers, ok_statuses=ok,
            retry_5xx=True,
        )

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        url = self._url(self._key(name))
        try:
            _s, headers, _b = await self._signed("HEAD", url, ok=(200,))
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        return BlobInfo(int(headers.get("Content-Length", 0)))

    async def download(self, namespace: str, name: str) -> bytes:
        url = self._url(self._key(name))
        try:
            _s, _h, body = await self._signed("GET", url, ok=(200,))
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise
        return body

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        url = self._url(self._key(name))
        await self._signed("PUT", url, data=data, ok=(200, 201, 204))

    async def upload_file(self, namespace: str, name: str, path: str) -> None:
        """Multipart upload for large blobs (S3 caps a single PUT at
        5 GiB, and buffering a multi-GB docker layer for one PUT is a
        memory cliff); small files take the single-PUT fast path. Part
        reads stream off disk one part at a time -- peak memory is one
        part, not the blob."""
        size = await asyncio.to_thread(os.path.getsize, path)
        if size <= self.multipart_threshold:
            def _read() -> bytes:
                with open(path, "rb") as f:
                    return f.read()

            await self.upload(namespace, name, await asyncio.to_thread(_read))
            return

        url = self._url(self._key(name))
        _s, _h, body = await self._signed(
            "POST", f"{url}?uploads", ok=(200,)
        )
        upload_id = next(
            (e.text for e in ET.fromstring(body).iter()
             if e.tag.endswith("UploadId")),
            None,
        )
        if not upload_id:
            raise HTTPError("POST", f"{url}?uploads", 500, b"no UploadId")
        try:
            etags: list[str] = []
            part_num = 0
            # open() off-loop too: on a cold NFS/network mount the open
            # alone can stall the loop for the full mount timeout.
            with await asyncio.to_thread(open, path, "rb") as f:
                while True:
                    chunk = await asyncio.to_thread(
                        f.read, self.multipart_part_size
                    )
                    if not chunk:
                        break
                    part_num += 1
                    part_url = (
                        f"{url}?partNumber={part_num}&uploadId="
                        f"{urllib.parse.quote(upload_id, safe='')}"
                    )
                    _ps, ph, _pb = await self._signed(
                        "PUT", part_url, data=chunk, ok=(200,)
                    )
                    # Case-insensitive: the HTTP client hands back a plain
                    # dict and servers spell it ETag/Etag/etag. The old
                    # exact-key lookup silently embedded <ETag></ETag> --
                    # real S3 rejects that at complete-time, far from here.
                    etag = next(
                        (v for k, v in ph.items() if k.lower() == "etag"),
                        "",
                    ).strip('"')
                    if not etag:
                        # Fail HERE, not at complete-time: an empty <ETag>
                        # in CompleteMultipartUpload produces a confusing
                        # S3 error far from the part that caused it.
                        raise HTTPError(
                            "PUT", part_url, 500,
                            f"part {part_num}: no ETag in response".encode(),
                        )
                    etags.append(etag)
            complete = "<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{i + 1}</PartNumber>"
                f"<ETag>{etag}</ETag></Part>"
                for i, etag in enumerate(etags)
            ) + "</CompleteMultipartUpload>"
            done_url = (
                f"{url}?uploadId={urllib.parse.quote(upload_id, safe='')}"
            )
            _s, _h, body = await self._signed(
                "POST", done_url, data=complete.encode(), ok=(200,)
            )
            # S3 reports complete-time failures inside a 200 body.
            if b"<Error>" in body:
                raise HTTPError("POST", done_url, 500, body)
        except BaseException:
            # Abort so the bucket doesn't accrete billed orphan parts; the
            # original failure is what the caller needs to see.
            try:
                await self._signed(
                    "DELETE",
                    f"{url}?uploadId="
                    f"{urllib.parse.quote(upload_id, safe='')}",
                    ok=(200, 204),
                )
            except Exception:
                _log.warning(
                    "multipart abort failed; billed orphan parts may"
                    " remain in the bucket", exc_info=True,
                )
            raise

    async def download_to_file(
        self, namespace: str, name: str, dest_path: str
    ) -> int:
        """Streamed GET straight to disk (O(chunk) memory for any blob)."""
        url = self._url(self._key(name))
        headers = sigv4_headers(
            "GET", url,
            access_key=self.access_key, secret_key=self.secret_key,
            region=self.region, service=self.service,
        )
        try:
            return await self._http.get_to_file(url, dest_path, headers=headers)
        except HTTPError as e:
            if e.status == 404:
                raise BlobNotFoundError(name) from None
            raise

    async def list(self, prefix: str) -> list[str]:
        """ListObjectsV2 with continuation; returns full keys under
        ``root``-joined prefix."""
        out: list[str] = []
        token: str | None = None
        key_prefix = f"{self.root}/{prefix}" if self.root else prefix
        while True:
            query = {"list-type": "2", "prefix": key_prefix}
            if token:
                query["continuation-token"] = token
            url = (
                f"{self.endpoint}/{self.bucket}?"
                + urllib.parse.urlencode(sorted(query.items()))
            )
            _s, _h, body = await self._signed("GET", url, ok=(200,))
            ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
            root = ET.fromstring(body)
            # Tolerate both namespaced (AWS) and bare (fakes) XML.
            keys = [e.text for e in root.iter() if e.tag.endswith("Key")]
            out.extend(k for k in keys if k)
            truncated = next(
                (e.text for e in root.iter() if e.tag.endswith("IsTruncated")),
                "false",
            )
            token = next(
                (e.text for e in root.iter()
                 if e.tag.endswith("NextContinuationToken")),
                None,
            )
            if truncated != "true" or not token:
                return out

    async def close(self) -> None:
        await self._http.close()


@register_backend("gcs")
def _gcs_factory(config: dict) -> S3Backend:
    """GCS via the S3-interoperable XML API (HMAC keys)."""
    config = dict(config)
    config.setdefault("endpoint", "https://storage.googleapis.com")
    config.setdefault("region", "auto")
    return S3Backend(config)

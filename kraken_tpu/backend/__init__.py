"""Pluggable storage backends (S3/GCS/HDFS-class remote stores).

Mirrors uber/kraken ``lib/backend`` (``Client`` {Stat, Download, Upload,
List}, self-registering factories, ``Manager`` resolving namespace regex ->
client with per-backend bandwidth caps) -- upstream path, unverified;
SURVEY.md SS2.3. The origin writes back committed blobs here and fills
cache misses from here; build-index persists tags here.
"""

from kraken_tpu.backend.base import (
    BackendClient,
    BackendError,
    BlobNotFoundError,
    Manager,
    register_backend,
)

__all__ = [
    "BackendClient",
    "BackendError",
    "BlobNotFoundError",
    "Manager",
    "register_backend",
]

# Import for registration side effects.
import kraken_tpu.backend.filebackend  # noqa: E402,F401
import kraken_tpu.backend.httpbackend  # noqa: E402,F401
import kraken_tpu.backend.testfs  # noqa: E402,F401
import kraken_tpu.backend.shadowbackend  # noqa: E402,F401
import kraken_tpu.backend.s3backend  # noqa: E402,F401  (also: gcs)
import kraken_tpu.backend.hdfsbackend  # noqa: E402,F401
import kraken_tpu.backend.registrybackend  # noqa: E402,F401

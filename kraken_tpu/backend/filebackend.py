"""Local-filesystem backend: the simplest durable store.

No direct reference analog (the reference's closest is testfs); used for
single-host deployments and as the default herd backend when no object
store exists.
"""

from __future__ import annotations

import asyncio
import os
import uuid

from kraken_tpu.backend.base import (
    BackendClient,
    BlobInfo,
    BlobNotFoundError,
    register_backend,
)
from kraken_tpu.backend.namepath import get_pather
from kraken_tpu.utils import failpoints


@register_backend("file")
class FileBackend(BackendClient):
    def __init__(self, config: dict):
        self.root = config["root"]
        self._pather = get_pather(config.get("pather", "identity"))
        os.makedirs(self.root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.root, self._pather("", name))

    async def stat(self, namespace: str, name: str) -> BlobInfo:
        try:
            return BlobInfo(os.path.getsize(self._path(name)))
        except FileNotFoundError:
            raise BlobNotFoundError(name) from None

    async def download(self, namespace: str, name: str) -> bytes:
        # Failpoint backend.file.download: a flaky durable store --
        # blobrefresh/writeback retry planes must surface and retry it,
        # never translate it into "not found".
        if failpoints.fire("backend.file.download"):
            import errno

            raise OSError(errno.EIO, "failpoint backend.file.download", name)
        def _read() -> bytes:
            with open(self._path(name), "rb") as f:
                return f.read()

        try:
            # Whole-blob disk read off the event loop: backends serve
            # read-through misses mid-pull, and a multi-MB sync read
            # here parks every conn pump in the process.
            return await asyncio.to_thread(_read)
        except FileNotFoundError:
            raise BlobNotFoundError(name) from None

    async def upload(self, namespace: str, name: str, data: bytes) -> None:
        if failpoints.fire("backend.file.upload"):
            import errno

            raise OSError(errno.ENOSPC, "failpoint backend.file.upload", name)
        path = self._path(name)

        def _write() -> None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # Unique tmp per call: now that writes run off-loop they can
            # interleave, and two same-name uploads sharing one ".tmp"
            # would race replace() into a spurious FileNotFoundError.
            tmp = f"{path}.tmp.{uuid.uuid4().hex[:8]}"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        await asyncio.to_thread(_write)

    async def list(self, prefix: str) -> list[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

"""Announce pacing: due-time queue with a global rate cap.

Mirrors uber/kraken ``lib/torrent/scheduler/announcequeue`` (ready/pending
rotation so announce load is O(configured rate), not O(torrents)) --
upstream path, unverified; SURVEY.md SS2.2. Rebuilt as a due-time min-heap
drained by one pump task: a 10k-torrent seeding agent emits at most
``max_rate`` announces/second, oldest-due first (the heap order IS the
ready/pending rotation), instead of one announce task per torrent firing
every interval.

Time-budget contract (round 8): every announce this queue's pump fires
runs under the tracker client's total deadline
(``rpc.announce_timeout_seconds`` -> utils/deadline.Deadline), so a hung
tracker socket exhausts ONE budget and re-enters the heap at the next
interval -- the pump itself never blocks on a wedged announce (it spawns
per-announce tasks), and no key can wedge the rotation.

Failure-backoff contract (round 12, the tracker HA plane): a FAILED
announce re-enters the heap on a per-torrent decorrelated-jitter delay
capped at the announce interval (scheduler ``_announce_once``), never on
the fixed tick -- so a tracker death does not synchronize every
torrent's retry into one storm, and with a tracker FLEET
(tracker/client.TrackerFleetClient) the jittered retry lands on the
next ring replica within ~one base delay.
"""

from __future__ import annotations

import heapq
from typing import Hashable


class AnnounceQueue:
    """Min-heap of (due, seq, key). Not thread-safe: event-loop only."""

    def __init__(self):
        self._heap: list[tuple[float, int, Hashable]] = []
        self._due: dict[Hashable, float] = {}  # current due time per key
        self._seq = 0

    def schedule(self, key: Hashable, due: float) -> None:
        """(Re-)schedule ``key`` at ``due``; an earlier entry wins (a
        download wanting peers NOW must not wait out a seed interval)."""
        current = self._due.get(key)
        if current is not None and current <= due:
            return
        self._due[key] = due
        self._seq += 1
        heapq.heappush(self._heap, (due, self._seq, key))

    def remove(self, key: Hashable) -> None:
        """Forget ``key`` (stale heap entries are skipped lazily on pop)."""
        self._due.pop(key, None)

    def pop_ready(self, now: float, limit: int) -> list[Hashable]:
        """Up to ``limit`` keys due at ``now``, oldest-due first. Popped
        keys are NOT rescheduled -- the announcer re-schedules after the
        announce returns (with the tracker-provided interval)."""
        out: list[Hashable] = []
        while self._heap and len(out) < limit:
            due, _seq, key = self._heap[0]
            if due > now:
                break
            heapq.heappop(self._heap)
            # Skip stale entries: removed keys, or keys superseded by an
            # earlier re-schedule (the live due time differs).
            if self._due.get(key) != due:
                continue
            del self._due[key]
            out.append(key)
        return out

"""The torrent scheduler: public ``download()`` + swarm orchestration.

Mirrors uber/kraken ``lib/torrent/scheduler`` (single event loop owning all
torrent state; blocking ``Download(namespace, digest)``; announce ticks;
conn management; seeding-by-existence for origins) -- upstream path,
unverified; SURVEY.md SS2.2/SS3.1. The reference's single-goroutine
invariant maps to the asyncio loop; its event structs map to plain awaits.

Collaborators are injected as small interfaces so in-process swarm tests
(SURVEY.md SS4 tier 3) can fake the tracker:

- ``metainfo_client.get(namespace, digest) -> MetaInfo``
- ``announce_client.announce(digest, info_hash, namespace, complete)
  -> (list[PeerInfo], interval_seconds)``
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import random
import secrets
from typing import Optional, Protocol

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import InfoHash, MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.p2p.conn import (
    Conn,
    ConnClosedError,
    HandshakeResult,
    LeechConnProxy,
    PeerBusyError,
    handshake_inbound,
    handshake_outbound,
)
from kraken_tpu.p2p.announcequeue import AnnounceQueue
from kraken_tpu.p2p.connstate import ConnState, ConnStateConfig
from kraken_tpu.p2p.dispatch import Dispatcher
from kraken_tpu.p2p.networkevent import NoopProducer, Producer
from kraken_tpu.p2p.pex import (
    MAX_ENTRIES_PER_MESSAGE,
    KnownPeers,
    PeerCache,
    PexConfig,
    PexManager,
)
from kraken_tpu.p2p.piecerequest import RequestManager
from kraken_tpu.p2p.shardpool import ShardPool
from kraken_tpu.p2p.storage import Torrent
from kraken_tpu.p2p.wire import Message, WireError, send_message


from kraken_tpu.utils import failpoints, trace
from kraken_tpu.utils.backoff import DecorrelatedJitter
from kraken_tpu.utils.bandwidth import BandwidthLimiter
from kraken_tpu.utils.bufpool import BufferPool
from kraken_tpu.utils.dedup import RequestCoalescer
from kraken_tpu.utils.metrics import REGISTRY, FailureMeter
from kraken_tpu.utils.slo import CANARY_NAMESPACE, SLO

_log = logging.getLogger("kraken.p2p")

# StreamReader buffer high-water mark for P2P conns. asyncio's 64 KiB
# default pauses the transport ~16x inside one 1 MiB piece frame
# (pause/resume flow-control round-trips cost ~20% pair goodput,
# measured -- PERF.md round-5 pair profile); 4 MiB holds a whole piece.
_WIRE_BUF = 4 << 20

_announce_failures = FailureMeter(
    "announce_failures_total",
    "Tracker announce attempts that raised (retried next interval)",
    _log,
)


class _AtCapacity(Exception):
    """Inbound conn rejected for capacity (accept path sends a busy frame)."""


class MetaInfoClient(Protocol):
    async def get(self, namespace: str, d: Digest) -> MetaInfo: ...


class AnnounceClient(Protocol):
    async def announce(
        self, d: Digest, h: InfoHash, namespace: str, complete: bool
    ) -> tuple[list[PeerInfo], float]: ...


class TorrentArchive(Protocol):
    def create_torrent(self, metainfo: MetaInfo) -> Torrent: ...


class SchedulerConfig:
    def __init__(
        self,
        announce_interval_seconds: float = 3.0,
        dial_timeout_seconds: float = 5.0,
        retry_tick_seconds: float = 2.0,
        conn_state: ConnStateConfig | None = None,
        seed_on_complete: bool = True,
        max_announce_rate: float = 100.0,
        announce_tick_seconds: float = 0.2,
        seed_announce_interval_seconds: float | None = None,
        piece_pipeline_limit: int = 16,
        piece_timeout_seconds: float = 8.0,
        conn_churn_idle_seconds: float = 4.0,
        wire_send_batch: int = 16,
        bufpool_budget_mb: int = 256,
        data_plane_workers: int = 0,
        leech_workers: int = 0,
        leech_ring_mb: int = 32,
        max_announce_inflight: int = 32,
    ):
        self.announce_interval = announce_interval_seconds
        self.dial_timeout = dial_timeout_seconds
        self.retry_tick = retry_tick_seconds
        self.conn_state = conn_state or ConnStateConfig()
        self.seed_on_complete = seed_on_complete
        # Announce pacing (announcequeue): the global cap keeps announce
        # load O(rate) however many torrents seed; complete torrents
        # re-announce on the longer seed interval.
        self.max_announce_rate = max_announce_rate
        self.announce_tick = announce_tick_seconds
        # 3x, not more: seeders must re-announce inside the tracker's peer
        # TTL (default 30 s vs 9 s here) or they vanish from handouts.
        self.seed_announce_interval = (
            seed_announce_interval_seconds
            if seed_announce_interval_seconds is not None
            else announce_interval_seconds * 3
        )
        # In-flight piece requests per conn. Measured (bench_swarm, loopback
        # pair): 4 -> 71 MB/s, 16 -> 82, 64 -> 82 -- 16 saturates the
        # request-response turnaround without deep per-peer buffering.
        self.piece_pipeline_limit = piece_pipeline_limit
        self.piece_timeout = piece_timeout_seconds
        self.conn_churn_idle = conn_churn_idle_seconds
        # Wire-plane knobs (round 7, docs/OPERATIONS.md "Wire plane"):
        # max frames corked into one vectored send per drain(), and the
        # recv payload pool's retained-byte budget.
        self.wire_send_batch = wire_send_batch
        self.bufpool_budget_mb = bufpool_budget_mb
        # Multi-core seed-serve plane (p2p/shardpool.py; docs/
        # OPERATIONS.md "Data-plane workers"): fork this many worker
        # processes and hand them seed-only inbound conns, served via
        # sendfile off the main loop. 0 = everything on the main loop
        # (the pre-round-8 behavior). SIGHUP-resizable.
        self.data_plane_workers = data_plane_workers
        # Multi-core DOWNLOAD plane (p2p/shardpool.py leech mode; docs/
        # OPERATIONS.md "Leech shard plane"): fork this many download
        # workers; active-download conns hand off post-handshake, their
        # recv pump + pwrite run off the main loop and piece payloads
        # come back through a shared-memory ring for batched verify.
        # 0 = downloads stay on the main loop. SIGHUP-resizable.
        # leech_ring_mb sizes EACH worker's ring (slot granularity 1 MiB
        # classes; a torrent whose piece length exceeds one slot stays
        # on the main loop).
        self.leech_workers = leech_workers
        self.leech_ring_mb = leech_ring_mb
        # PER-AGENT announce concurrency cap. The rate cap bounds how
        # many announces START per second; during a full tracker outage
        # every in-flight announce hangs to its timeout, and without a
        # concurrency bound N failing torrents stack N timed-out walks
        # -- a storm of busywork against dead hosts, re-synchronized at
        # every revival. The per-torrent decorrelated-jitter backoff
        # desyncs the retries; this bounds how many run at once.
        self.max_announce_inflight = max(1, max_announce_inflight)

    @classmethod
    def from_dict(cls, doc: dict) -> "SchedulerConfig":
        """Build from the YAML ``scheduler:`` section; ``conn_state`` is a
        nested dict of ConnStateConfig fields."""
        doc = dict(doc)
        conn = doc.pop("conn_state", None)
        import inspect

        allowed = set(inspect.signature(cls.__init__).parameters) - {
            "self", "conn_state"
        }
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"unknown scheduler config keys: {sorted(unknown)}")
        return cls(
            conn_state=ConnStateConfig.from_dict(conn) if conn else None,
            **doc,
        )


class _TorrentControl:
    def __init__(
        self,
        torrent: Torrent,
        namespace: str,
        dispatcher: Dispatcher,
        known_peers_cap: int = 256,
    ):
        self.torrent = torrent
        self.namespace = namespace
        self.dispatcher = dispatcher
        self.tasks: set[asyncio.Task] = set()
        # Dialable-peer book for the PEX plane (p2p/pex.py): fed by
        # tracker announces, handshakes carrying a listen port, gossip,
        # and the peercache -- what this node gossips onward and what
        # the peercache persists for crash redials.
        self.known_peers = KnownPeers(cap=known_peers_cap)
        # The download's trace context (utils/trace.py): announce and
        # dial tasks are spawned from long-lived pump loops, OUTSIDE the
        # downloader's contextvar scope, so the control carries the
        # parent explicitly for them to join. None for pure seeders.
        self.trace_parent: trace.ParentContext | None = None
        # Decorrelated-jitter carry for FAILED announces (0 = healthy):
        # a dead tracker must not make every torrent's retry land on the
        # same tick fleet-wide (the synchronized-storm shape), and the
        # first retry should come FASTER than a full interval so
        # failover finds peers quickly.
        self.announce_backoff = 0.0

    def spawn(self, coro) -> asyncio.Task:
        """Track a task for cleanup; finished tasks self-prune (a seeding
        control dials on every announce tick -- an append-only list would
        grow forever)."""
        task = asyncio.create_task(coro)
        self.tasks.add(task)
        task.add_done_callback(self.tasks.discard)
        return task

    def cancel_tasks(self) -> None:
        for t in list(self.tasks):
            t.cancel()


class Scheduler:
    """One per process. Owns the listening socket and all torrent state."""

    def __init__(
        self,
        peer_id: PeerID,
        ip: str,
        port: int,
        archive: TorrentArchive,
        metainfo_client: MetaInfoClient,
        announce_client: AnnounceClient,
        config: SchedulerConfig | None = None,
        bandwidth: BandwidthLimiter | None = None,
        events: Producer | None = None,
        is_origin: bool = False,
        metainfo_resolver=None,
        delta=None,  # p2p.delta.DeltaPlanner (agents; optional)
        pex: PexConfig | None = None,
        peercache_path: str | None = None,
    ):
        self.peer_id = peer_id
        self.ip = ip
        self.port = port
        self.archive = archive
        self.metainfo_client = metainfo_client
        self.announce_client = announce_client
        self.config = config or SchedulerConfig()
        self.bandwidth = bandwidth
        self.events = events or NoopProducer()
        self.is_origin = is_origin
        # Origin side: resolve a blob digest hex -> MetaInfo for inbound
        # handshakes on blobs we seed but have no live control for.
        self._metainfo_resolver = metainfo_resolver
        # Delta-transfer plane (p2p/delta.py): when set, downloads run a
        # prefill pass first -- pieces assembled from a local near-
        # duplicate base (plus origin byte-range fetches) land in the
        # piece bitfield before the swarm pull, which then fetches only
        # what delta could not cover. Gated inside the planner on its
        # live-reloadable config; a prefill failure never fails the pull.
        self._delta = delta
        self._convert_tasks: set[asyncio.Task] = set()  # strong refs
        self.conn_state = ConnState(self.config.conn_state)
        # Which Conn instance owns each conn-state active slot: a stale
        # conn's close must never release a slot a newer conn has taken.
        self._conn_owners: dict[tuple[PeerID, InfoHash], Conn] = {}
        self._controls: dict[InfoHash, _TorrentControl] = {}
        # digest -> info hash: unseed must be O(1), not a scan -- a
        # watermark eviction sweep unseeds many blobs back to back.
        self._digest_to_hash: dict[Digest, InfoHash] = {}
        self._coalescer: RequestCoalescer = RequestCoalescer()
        # One payload pool per scheduler, shared by every conn: the piece
        # pipeline bounds concurrent leases, the budget bounds retained
        # free bytes (utils/bufpool.py).
        self._bufpool = BufferPool(
            budget_bytes=self.config.bufpool_budget_mb << 20
        )
        self._server: Optional[asyncio.base_events.Server] = None
        # Multi-core seed-serve plane (p2p/shardpool.py): created at
        # start() when data_plane_workers > 0; seed-only inbound conns
        # are handed to worker processes via fd passing and served with
        # sendfile, off this loop entirely.
        self._shardpool: Optional[ShardPool] = None
        # Multi-core download plane (shardpool in leech mode): created at
        # start() when leech_workers > 0; active-download conns hand off
        # post-handshake and the dispatcher drives a LeechConnProxy --
        # recv pump, frame parse, and pwrite all run in the workers,
        # piece payloads come home through each worker's shared ring.
        self._leech_pool: Optional[ShardPool] = None
        self._announce_queue = AnnounceQueue()
        self._announce_pump_task: Optional[asyncio.Task] = None
        self._announce_tasks: set[asyncio.Task] = set()
        # PEX gossip plane (p2p/pex.py): receive is merged behind the
        # connstate blacklist in _on_pex; the send pump gossips deltas
        # on existing conns. SIGHUP live-reloads via reload_pex().
        self.pex_config = pex or PexConfig()
        self._pex = PexManager(self.pex_config)
        self._pex_task: Optional[asyncio.Task] = None
        # Disk-backed last-known-peers cache: loaded once at start(),
        # merged+flushed periodically, seeding redials (and serving
        # metainfo) across an agent restart during a tracker outage.
        self._peercache: Optional[PeerCache] = (
            PeerCache(
                peercache_path,
                ttl_seconds=self.pex_config.peercache_ttl_seconds,
            )
            if peercache_path and self.pex_config.peercache
            else None
        )
        self._peercache_doc: dict[str, dict] = {}
        self._peercache_task: Optional[asyncio.Task] = None
        # Lameduck drain (docs/OPERATIONS.md "Degradation plane"): stop
        # announcing and refuse NEW conns, but keep serving established
        # ones so in-flight pieces finish. Entered by SIGTERM or
        # POST /debug/lameduck; the tracker's peer TTL then ages this
        # node out of handouts.
        self.lameduck = False
        # Terminal: set by stop(). A download racing stop past its
        # metainfo await must not create a fresh control (whose
        # _retry_loop nothing would ever cancel -- stop already swept
        # self._controls).
        self._stopped = False

    # -- lifecycle ---------------------------------------------------------

    def reload(self, config: SchedulerConfig) -> None:
        """Live config swap (the reference's ReloadableScheduler). Pacing,
        timeouts, and conn limits apply from the next tick or admission
        decision; per-torrent dispatchers keep their pipeline settings
        until their torrent is recreated (new torrents use the new
        values). No torrent state is dropped. The seed-serve worker pool
        resizes live: grown shards spawn, shrunk shards drain and exit."""
        self.config = config
        self.conn_state.reconfigure(config.conn_state)
        self._bufpool.set_budget(config.bufpool_budget_mb << 20)
        pool = getattr(self, "_shardpool", None)
        if pool is not None:
            pool.reconfigure(config.conn_churn_idle)
            pool.resize(config.data_plane_workers)
        elif (
            config.data_plane_workers > 0
            and getattr(self, "_server", None) is not None
        ):
            self._start_shardpool()
        leech = getattr(self, "_leech_pool", None)
        if leech is not None:
            leech.reconfigure(config.conn_churn_idle)
            leech.resize(config.leech_workers)
        elif (
            config.leech_workers > 0
            and getattr(self, "_server", None) is not None
        ):
            self._start_leech_pool()
        _log.info("scheduler config reloaded")

    def reload_pex(self, config: PexConfig) -> None:
        """Live swap of the YAML ``pex:`` section (SIGHUP): cadence,
        budgets, and the enable switches apply from the next tick or
        received frame; dedup state survives (it is correctness, not
        tuning). The peercache path is fixed at construction."""
        self.pex_config = config
        self._pex.reconfigure(config)
        _log.info("pex config reloaded")

    def _start_shardpool(self) -> None:
        self._shardpool = ShardPool(
            self.config.data_plane_workers,
            churn_idle_seconds=self.config.conn_churn_idle,
            on_conn_closed=self._shard_conn_closed,
            component="origin" if self.is_origin else "agent",
        )
        self._shardpool.start()

    def _start_leech_pool(self) -> None:
        # Slots match the metainfo generator's default 4 MiB piece
        # class (origin/metainfogen.py): ring_mb / 4 MiB slots per
        # worker. The slab is anonymous MAP_SHARED -- pages materialize
        # on first touch, so oversized slots for short-piece torrents
        # cost address space, not RSS. Torrents with longer pieces
        # (8/16 MiB tiers for >= 2 GiB blobs) skip the plane at
        # handoff gating.
        slot_bytes = 4 << 20
        self._leech_pool = ShardPool(
            self.config.leech_workers,
            churn_idle_seconds=self.config.conn_churn_idle,
            component=(
                "origin-leech" if self.is_origin else "agent-leech"
            ),
            leech=True,
            ring_slots=max(1, (self.config.leech_ring_mb << 20) // slot_bytes),
            slot_bytes=slot_bytes,
        )
        self._leech_pool.start()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._accept, host=self.ip, port=self.port, limit=_WIRE_BUF
        )
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        if self.config.data_plane_workers > 0:
            self._start_shardpool()
        if self.config.leech_workers > 0:
            self._start_leech_pool()
        self._announce_pump_task = asyncio.create_task(self._announce_pump())
        self._pex_task = asyncio.create_task(self._pex_pump())
        if self._peercache is not None:
            # Load off-loop (disk read); tolerant of anything on disk.
            self._peercache_doc = await asyncio.to_thread(
                self._peercache.load
            )
            self._peercache_task = asyncio.create_task(
                self._peercache_flush_loop()
            )

    async def stop(self) -> None:
        self._stopped = True
        if self._announce_pump_task is not None:
            self._announce_pump_task.cancel()
        if self._pex_task is not None:
            self._pex_task.cancel()
        if self._peercache_task is not None:
            self._peercache_task.cancel()
        if self._peercache is not None:
            # Final snapshot while the controls still exist: a planned
            # restart must resume with the freshest peer book, not the
            # last periodic flush's.
            with contextlib.suppress(Exception):
                await self._flush_peercache()
        for t in list(self._announce_tasks):
            t.cancel()
        for t in list(self._convert_tasks):
            # Safe to cut: convert_to_chunks runs inside ONE to_thread
            # hop, so a cancel lands before it starts or after it
            # finished -- never mid-conversion.
            t.cancel()
        for ctl in list(self._controls.values()):
            ctl.cancel_tasks()
            ctl.dispatcher.close()
        self._controls.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._shardpool is not None:
            await self._shardpool.stop()
            self._shardpool = None
        if self._leech_pool is not None:
            await self._leech_pool.stop()
            self._leech_pool = None

    @property
    def addr(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def num_active_conns(self) -> int:
        """Live peer conns -- the drain loop's quiesce signal. Counts
        BOTH halves of the data plane: main-loop conns and the ones
        handed to worker shards (a drain must wait for in-flight worker
        serves exactly like in-flight dispatcher pieces). Leech-shard
        conns are NOT added here: their proxies live in _conn_owners
        already (the dispatcher adopted them), so adding the leech
        pool's count would double-book every one."""
        shard = self._shardpool.num_conns if self._shardpool else 0
        return len(self._conn_owners) + shard

    def enter_lameduck(self) -> None:
        """Drain mode: seed announces stop (the tracker's peer TTL ages
        this node out of handouts) and new INBOUND conns are refused --
        but in-flight downloads keep announcing and dialing: "let
        in-flight work finish" includes a download that has not found
        its peers yet, and the HTTP layer already refuses NEW download
        requests while draining. Established conns keep serving until
        they complete and churn out; assembly's drain() waits on
        :attr:`num_active_conns`."""
        self.lameduck = True
        if self._shardpool is not None:
            # Fan the drain out: worker shards stop taking handoffs,
            # let in-flight serves finish, and churn their conns out --
            # the same SIGTERM semantics as the main loop.
            self._shardpool.enter_lameduck()
        if self._leech_pool is not None:
            # Same for the download plane: no new handoffs; established
            # leech conns keep pulling until their download completes
            # (in-flight work finishing IS the point of the drain).
            self._leech_pool.enter_lameduck()
        _log.info("scheduler entering lameduck drain")

    # -- public API --------------------------------------------------------

    async def download(self, namespace: str, d: Digest) -> None:
        """Download blob ``d`` via the swarm; returns when it is complete
        in local storage. Concurrent calls for one blob coalesce."""
        await self._coalescer.get(d.hex, lambda: self._download(namespace, d))

    async def _download(self, namespace: str, d: Digest) -> None:
        start = asyncio.get_running_loop().time()
        # The pull's root-most p2p span: a child of the HTTP server span
        # when the download came through an agent endpoint, a fresh
        # sampled-or-not root for direct callers. Announce/dial tasks
        # join via ctl.trace_parent (they run outside this context).
        with trace.span(
            "p2p.download", digest=d.hex[:12], namespace=namespace,
        ) as sp:
            plan_t0 = asyncio.get_running_loop().time()
            try:
                metainfo = await self.metainfo_client.get(namespace, d)
            except asyncio.CancelledError:
                raise
            except Exception:
                # Tracker dark (total outage): the peercache may hold
                # this blob's metainfo from a pull that was in flight
                # before a restart -- the ONLY way a fresh boot can
                # rejoin its swarm with every tracker down. No cache
                # record: the original failure stands, typed as-is.
                metainfo = self._peercache_metainfo(d)
                if metainfo is None:
                    raise
                REGISTRY.counter(
                    "pex_peercache_metainfo_hits_total",
                    "Metainfo served from the peercache because every"
                    " tracker fetch failed",
                ).inc()
            if (
                self._delta is not None
                and metainfo.info_hash not in self._controls
            ):
                # Prefill BEFORE the control exists: the control's
                # Torrent (and its dispatcher's done future) must be
                # built from the post-prefill bitfield -- a fully
                # prefilled blob then completes without a single conn.
                try:
                    await self._delta.prefill(metainfo, namespace)
                except Exception:
                    _log.warning(
                        "delta prefill failed; full swarm pull",
                        extra={"digest": d.hex}, exc_info=True,
                    )
            plan_wall = asyncio.get_running_loop().time() - plan_t0
            ctl = self._get_or_create_control(metainfo, namespace)
            # Stage split for the torrent_summary rollup: "plan" is
            # everything before the swarm could move a byte (metainfo
            # fetch + delta prefill).
            ctl.dispatcher.stage_walls["plan"] += plan_wall
            if sp is not None and ctl.trace_parent is None:
                ctl.trace_parent = trace.ParentContext(
                    sp.trace_id, sp.span_id, sp.sampled
                )
            try:
                await asyncio.shield(ctl.dispatcher.done)
            finally:
                # The pull is over (or failed): seed-phase re-announces
                # must not keep joining -- and inflating -- the
                # download's trace for the torrent's whole seeding life;
                # from here they are their own sampled-or-not roots.
                ctl.trace_parent = None
        # Per-torrent lifecycle summary (the reference's torrentlog):
        # one line per completed download with the operative numbers.
        _log.info(
            "torrent complete",
            extra={
                "digest": d.hex,
                "namespace": namespace,
                "bytes": metainfo.length,
                "pieces": metainfo.num_pieces,
                "seconds": round(
                    asyncio.get_running_loop().time() - start, 3
                ),
                "peers": ctl.dispatcher.num_peers,
            },
        )
        # Become discoverable as a seeder immediately (still rate-paced).
        self._announce_queue.schedule(metainfo.info_hash, 0.0)
        if self._delta is not None:
            # Chunk-tier handover (store/chunkstore.py): a completed
            # pull whose recipe the prefill planner fetched converts to
            # manifest + refcounted chunks, so the NEXT near-duplicate
            # build stores only its unique bytes. A BACKGROUND task --
            # conversion re-reads the whole blob, and blocking here
            # would add seconds to every large pull's completion; every
            # serve path picks its representation atomically
            # (store/serve.py, open_cache_reader), so racing readers
            # are safe. Failures never fail the pull: the blob just
            # stays flat.
            t = asyncio.create_task(
                self._chunk_convert(metainfo, namespace)
            )
            self._convert_tasks.add(t)
            t.add_done_callback(self._convert_tasks.discard)
        if not self.config.seed_on_complete:
            # Download-only mode: tear the torrent down instead of
            # lazily seeding it (e.g. bandwidth-constrained edge agents).
            self._remove_control(metainfo.info_hash)

    async def _chunk_convert(self, metainfo: MetaInfo, namespace: str) -> None:
        try:
            await self._delta.chunk_completed(metainfo, namespace)
        except Exception:
            _log.warning(
                "chunk-tier conversion failed; blob stays flat",
                extra={"digest": metainfo.digest.hex}, exc_info=True,
            )

    def _remove_control(self, h: InfoHash) -> None:
        ctl = self._controls.pop(h, None)
        if ctl is None:
            return
        self._digest_to_hash.pop(ctl.torrent.metainfo.digest, None)
        if self._shardpool is not None:
            # Worker shards drop their long-lived blob fd and close the
            # torrent's conns gracefully (the remotes requeue elsewhere)
            # -- a seeder must not keep serving bytes it just evicted.
            self._shardpool.evict(ctl.torrent.metainfo.digest.hex)
        if self._leech_pool is not None:
            # Same fan-out on the download plane: leech workers hold a
            # writable fd on the .part -- it must not outlive the blob.
            self._leech_pool.evict(ctl.torrent.metainfo.digest.hex)
        self._announce_queue.remove(h)
        ctl.cancel_tasks()
        ctl.dispatcher.close()
        self.conn_state.clear_torrent(h)
        self.events.emit("remove_torrent", h.hex)

    def seed(self, metainfo: MetaInfo, namespace: str) -> None:
        """Start seeding a complete local blob (origin startup / post-
        download agents keep seeding automatically)."""
        self._get_or_create_control(metainfo, namespace)

    def seed_partial(self, metainfo: MetaInfo, namespace: str, path: str) -> None:
        """Seed a blob whose bytes are all on disk but NOT yet committed
        (serve-while-ingest): the torrent reads straight from the upload
        spool at ``path``. Pulls of a still-ingesting blob start now;
        :meth:`promote_partial` repoints at the cache path post-commit,
        :meth:`unseed` tears down if the commit fails."""
        torrent = Torrent(
            self.archive.store, metainfo, self.archive.verifier,
            complete=True, path=path,
        )
        self._get_or_create_control(metainfo, namespace, torrent=torrent)

    def promote_partial(self, d: Digest, path: str) -> None:
        """Commit landed: repoint blob ``d``'s spool-backed torrent at its
        committed cache path. No-op when no such torrent is live."""
        h = self._digest_to_hash.get(d)
        if h is None:
            return
        ctl = self._controls.get(h)
        if ctl is not None and getattr(ctl.torrent, "spool_backed", False):
            ctl.torrent.promote(path)

    def unseed(self, d: Digest) -> bool:
        """Stop seeding blob ``d`` (DELETE / cache eviction): the torrent
        control, its announces, and its conns go away -- a seeder must not
        keep advertising bytes it can no longer serve. False if no torrent
        for ``d`` is active."""
        h = self._digest_to_hash.get(d)
        if h is None:
            return False
        self._remove_control(h)
        return True

    def stage_walls(self, d: Digest) -> dict | None:
        """The PR-8 per-pull stage split (plan/dial/piece_wait/verify/
        write walls) of blob ``d``'s live torrent, or None once the
        control is gone.  The canary prober (utils/canary.py) reads it
        right after a probe pull to attribute where a slow canary spent
        its time."""
        h = self._digest_to_hash.get(d)
        if h is None:
            return None
        ctl = self._controls.get(h)
        if ctl is None:
            return None
        return ctl.dispatcher.stage_split()

    # -- torrent control ---------------------------------------------------

    def _get_or_create_control(
        self, metainfo: MetaInfo, namespace: str, torrent=None
    ) -> _TorrentControl:
        h = metainfo.info_hash
        ctl = self._controls.get(h)
        if ctl is not None:
            return ctl
        if self._stopped:
            # stop() already swept the controls; creating one now would
            # leak its retry loop (and re-announce a dead node).
            raise RuntimeError("scheduler is stopped")
        if torrent is None:
            torrent = self.archive.create_torrent(metainfo)
        dispatcher = Dispatcher(
            torrent,
            requests=RequestManager(
                pipeline_limit=self.config.piece_pipeline_limit,
                timeout_seconds=self.config.piece_timeout,
            ),
            on_peer_failure=lambda pid, reason: self._peer_failed(pid, h, reason),
            churn_idle_seconds=self.config.conn_churn_idle,
            events=self.events,
            on_peer_exchange=lambda pid, hdr: self._on_pex(pid, h, hdr),
        )
        ctl = _TorrentControl(
            torrent, namespace, dispatcher,
            known_peers_cap=self.pex_config.max_known_peers,
        )
        self._controls[h] = ctl
        self._digest_to_hash[torrent.metainfo.digest] = h
        # First announce ASAP (downloads need peers now); re-announces are
        # paced by the queue pump under the global rate cap.
        self._announce_queue.schedule(h, 0.0)
        ctl.spawn(self._retry_loop(ctl))
        self._seed_from_peercache(ctl)
        self.events.emit(
            "add_torrent", h.hex, blob=metainfo.name, complete=torrent.complete()
        )
        return ctl

    def _peer_failed(self, peer_id: PeerID, h: InfoHash, reason: str) -> None:
        self.conn_state.blacklist.add(peer_id, h)
        self.conn_state.remove(peer_id, h)
        self.events.emit("blacklist_conn", h.hex, peer=peer_id.hex, reason=reason)

    # -- peer exchange (PEX) -----------------------------------------------

    def _on_pex(self, sender: PeerID, h: InfoHash, header: dict) -> None:
        """One received PEER_EXCHANGE frame (sync, on the recv pump via
        the dispatcher). A ValueError out of ingest -- shape garbage or
        an entry flood -- propagates into the dispatcher's _fail_peer
        ban path, exactly like a bad piece. Accepted peers merge behind
        the SAME gates announces use: _maybe_dial goes through
        conn_state.add_pending, so a blacklisted peer gossiped back in
        stays blacklisted, and the token-bucket dial budget keeps even
        an honest gossip storm from flooding the dial queue."""
        ctl = self._controls.get(h)
        if ctl is None:
            return
        # Failpoint p2p.pex.drop: lossy gossip plane -- discovery must
        # still converge off later ticks / other senders.
        if failpoints.fire("p2p.pex.drop"):
            return
        if not self.pex_config.enabled:
            return
        now = asyncio.get_running_loop().time()
        fresh, drops = self._pex.ingest(h.hex, sender, header, now)
        src = f"gossip:{sender.hex}"
        for pid in drops:
            ctl.known_peers.drop(pid, src)
        for peer in fresh:
            if peer.peer_id == self.peer_id:
                continue
            if not ctl.known_peers.add(peer, src):
                continue  # book full of authoritative entries
            if ctl.torrent.complete():
                continue  # seeders learn addrs but never dial
            if not self._pex.try_dial_budget():
                continue
            self._maybe_dial(ctl, peer)

    async def _pex_pump(self) -> None:
        """ONE task gossips for every conn: each jittered tick computes
        per-conn deltas (what that conn has not heard yet, capped at the
        send budget) and spawns the sends -- never awaiting a send
        inline, so one stuck peer cannot stall the plane's cadence."""
        rng = random.Random()
        while True:
            cfg = self.pex_config  # re-read: reload_pex swaps it live
            interval = max(1.0, cfg.interval_seconds)
            await asyncio.sleep(
                interval * (1.0 + rng.uniform(-cfg.jitter, cfg.jitter))
            )
            if not cfg.send_enabled:
                continue
            self._gossip_tick()

    def _gossip_tick(self) -> None:
        frames = 0
        for key, conn in list(self._conn_owners.items()):
            pid, h = key
            ctl = self._controls.get(h)
            if ctl is None:
                continue
            added, dropped = self._pex.delta_for(
                key, pid, ctl.known_peers.snapshot()
            )
            # Failpoint p2p.pex.flood: a hostile peer ignoring the send
            # budget -- the RECEIVER must ban us (entry-count violation),
            # not balloon its dial queue.
            if failpoints.fire("p2p.pex.flood"):
                added = [
                    {"id": secrets.token_hex(20), "ip": "203.0.113.1",
                     "p": 1 + (i % 65000)}
                    for i in range(MAX_ENTRIES_PER_MESSAGE + 1)
                ]
            if not added and not dropped:
                continue
            frames += 1
            ctl.spawn(self._send_pex(conn, added, dropped))
        if frames:
            with trace.span("p2p.pex.gossip", frames=frames):
                pass

    async def _send_pex(
        self, conn: Conn, added: list[dict], dropped: list[str]
    ) -> None:
        with contextlib.suppress(ConnClosedError):
            await conn.send(Message.peer_exchange(added, dropped))

    # -- peercache (disk-backed last-known peers) --------------------------

    def _peercache_metainfo(self, d: Digest) -> MetaInfo | None:
        """Cached metainfo for blob ``d``, from a pull that was in
        flight when the cache was last flushed. None on any miss or
        decode problem (the cache must never add failure modes)."""
        for rec in self._peercache_doc.values():
            try:
                mi = MetaInfo.deserialize(rec["metainfo"].encode())
            except Exception:
                _log.debug(
                    "peercache record undecodable; skipped", exc_info=True
                )
                continue
            if mi.digest == d:
                return mi
        return None

    def _seed_from_peercache(self, ctl: _TorrentControl) -> None:
        """New incomplete control: seed its dial set with the cached
        last-known peers (TTL-aged at load). Dials ride the normal
        connstate gates; the first successful tracker announce then
        refreshes the book with authoritative records."""
        if ctl.torrent.complete():
            return
        rec = self._peercache_doc.get(ctl.torrent.info_hash.hex)
        if rec is None:
            return
        seeded = 0
        for peer in rec["peers"]:
            if peer.peer_id == self.peer_id:
                continue
            ctl.known_peers.add(peer, "cache")
            self._maybe_dial(ctl, peer)
            seeded += 1
        if seeded:
            REGISTRY.counter(
                "pex_peercache_seeds_total",
                "Dial candidates seeded from the disk peercache at"
                " torrent creation",
            ).inc(seeded)

    async def _peercache_flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.pex_config.peercache_flush_seconds)
            try:
                await self._flush_peercache()
            except asyncio.CancelledError:
                raise
            except Exception:
                _log.warning("peercache flush failed", exc_info=True)

    async def _flush_peercache(self) -> None:
        """Merge live in-flight torrents over the loaded doc (carried
        records keep their TTL clocks) and persist off-loop. Completed
        pulls drop out -- a restart serves them from the store."""
        if self._peercache is None:
            return
        doc = dict(self._peercache_doc)
        for h, ctl in list(self._controls.items()):
            if ctl.torrent.complete():
                doc.pop(h.hex, None)
                continue
            peers = [
                p for p in ctl.known_peers.snapshot()
                if p.peer_id != self.peer_id
            ]
            if not peers:
                continue
            doc[h.hex] = {
                "namespace": ctl.namespace,
                "metainfo": ctl.torrent.metainfo.serialize().decode(),
                "peers": peers,
            }
        self._peercache_doc = doc
        await asyncio.to_thread(self._peercache.save, doc)

    # -- announce / dial ---------------------------------------------------

    async def _announce_pump(self) -> None:
        """ONE task paces every torrent's announces (announcequeue): each
        tick drains at most rate*tick due torrents, oldest-due first, so
        tracker load is bounded by config however many torrents exist."""
        carry = 0.0  # fractional budget: caps below 1/tick must still hold
        while True:
            cfg = self.config  # re-read: reload() swaps the config live
            carry = min(
                carry + cfg.max_announce_rate * cfg.announce_tick,
                max(1.0, cfg.max_announce_rate),  # burst at most 1 s of budget
            )
            # Satellite cap: never more than max_announce_inflight walks
            # in flight PER AGENT. Healthy trackers finish announces in
            # milliseconds and never feel this; during a full outage it
            # is what keeps N failing torrents from stacking N hung
            # timeout walks (the rate cap only bounds starts).
            room = max(
                0, cfg.max_announce_inflight - len(self._announce_tasks)
            )
            budget = min(int(carry), room)
            carry -= budget
            now = asyncio.get_running_loop().time()
            for h in self._announce_queue.pop_ready(now, budget):
                ctl = self._controls.get(h)
                if ctl is None:
                    continue
                t = asyncio.create_task(self._announce_once(ctl))
                self._announce_tasks.add(t)
                t.add_done_callback(self._announce_tasks.discard)
            await asyncio.sleep(cfg.announce_tick)

    async def _announce_once(self, ctl: _TorrentControl) -> None:
        h = ctl.torrent.info_hash
        complete = ctl.torrent.complete()
        if self.lameduck and complete:
            # Draining seeders go dark (no reschedule: the tracker's
            # peer TTL forgets us); LEECHING announces keep flowing so
            # an in-flight download can still find its peers and finish
            # inside the drain window.
            return
        interval = (
            self.config.seed_announce_interval
            if complete
            else self.config.announce_interval
        )
        announce_t0 = asyncio.get_running_loop().time()
        try:
            # Child of the download's root span (the announce pump task
            # itself carries no context); seeders' re-announces become
            # their own sampled-or-not roots.
            with trace.span(
                "p2p.announce", ctl.trace_parent,
                info_hash=h.hex[:12], complete=complete,
            ):
                peers, interval_r = await self.announce_client.announce(
                    ctl.torrent.digest, h, ctl.namespace, complete
                )
            announce_wall = asyncio.get_running_loop().time() - announce_t0
            ctl.announce_backoff = 0.0  # healthy again: next failure is fresh
            if not complete and interval_r:
                interval = interval_r
            self.events.emit("announce", h.hex, returned=len(peers))
            for peer in peers:
                if peer.peer_id != self.peer_id:
                    # Authoritative handout: feeds the PEX gossip book
                    # (and the peercache snapshot behind it).
                    ctl.known_peers.add(peer, "tracker")
                self._maybe_dial(ctl, peer)
            # Announce SLI (utils/slo.py): client-side latency covers
            # the whole fleet walk -- failovers and breaker shedding
            # included -- which is what an agent actually experiences.
            # Recorded LAST in the try: an emit/dial failure must take
            # the except's bad-record path, never count the same
            # announce as both good and bad.
            SLO.record(
                "announce", True, announce_wall,
                canary=ctl.namespace == CANARY_NAMESPACE,
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            SLO.record(
                "announce", False,
                asyncio.get_running_loop().time() - announce_t0,
                canary=ctl.namespace == CANARY_NAMESPACE,
            )
            # Tracker hiccup: retry with per-torrent decorrelated-jitter
            # backoff, capped at the announce interval -- METERED (a
            # dead tracker must be visible on /metrics), and NEVER on a
            # fixed tick (a tracker death otherwise synchronizes every
            # torrent's retry into one storm at its revival).
            _announce_failures.record(f"announce {h.hex[:12]}", e)
            # Backoff-and-probe during a LATCHED fleet outage: with every
            # tracker dark (tracker/client.py outage latch) there is no
            # failover left to find, so retries stretch well past the
            # normal interval -- PEX carries discovery -- and each one
            # that does run doubles as the recovery probe. The latch
            # clears on the first success and cadence snaps back.
            outage = bool(getattr(self.announce_client, "outage", False))
            cap = interval * (8.0 if outage else 1.0)
            jitter = DecorrelatedJitter(
                base_seconds=min(1.0, interval), max_seconds=cap
            )
            ctl.announce_backoff = jitter.next(ctl.announce_backoff)
            interval = ctl.announce_backoff
            REGISTRY.counter(
                "announce_retry_backoffs_total",
                "Failed announces rescheduled with decorrelated-jitter"
                " backoff instead of the fixed interval",
            ).inc()
        if h in self._controls:
            self._announce_queue.schedule(
                h, asyncio.get_running_loop().time() + interval
            )

    def _maybe_dial(self, ctl: _TorrentControl, peer: PeerInfo) -> None:
        # Deliberately NOT lameduck-gated: dials only ever serve an
        # INCOMPLETE torrent (see the complete() check below), i.e. an
        # in-flight download -- exactly the work a drain lets finish.
        # New downloads are refused upstream at the HTTP layer.
        if peer.peer_id == self.peer_id:
            return
        # Complete torrents only serve; they never dial (origins and
        # seeding agents wait for inbound conns).
        if ctl.torrent.complete():
            return
        h = ctl.torrent.info_hash
        if not self.conn_state.add_pending(peer.peer_id, h):
            return
        ctl.spawn(self._dial(ctl, peer))

    async def _dial(self, ctl: _TorrentControl, peer: PeerInfo) -> None:
        # Stage split: "dial" is the connect+handshake wall, successful
        # or not -- a pull that spends its life redialing soft-busy
        # seeders shows it here, not as mystery wall time.
        t0 = asyncio.get_running_loop().time()
        try:
            await self._dial_inner(ctl, peer)
        finally:
            ctl.dispatcher.stage_walls["dial"] += (
                asyncio.get_running_loop().time() - t0
            )

    async def _dial_inner(self, ctl: _TorrentControl, peer: PeerInfo) -> None:
        h = ctl.torrent.info_hash
        # The dial span ADOPTS the conn: _adopt runs inside it, so the
        # conn's pumps (and every io task they spawn) inherit this
        # context -- piece requests/receives nest under the dial, and
        # the outbound handshake carries its traceparent to the remote.
        with trace.span(
            "p2p.dial", ctl.trace_parent,
            peer=f"{peer.ip}:{peer.port}", info_hash=h.hex[:12],
        ) as sp:
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(
                        peer.ip, peer.port, limit=_WIRE_BUF
                    ),
                    self.config.dial_timeout,
                )
                theirs = await handshake_outbound(
                    reader,
                    writer,
                    self.peer_id,
                    h,
                    ctl.torrent.metainfo.name,
                    ctl.namespace,
                    ctl.torrent.bitfield(),
                    ctl.torrent.num_pieces,
                    timeout=self.config.dial_timeout,
                    own_listen_port=self.port,
                )
            except (PeerBusyError, OSError, asyncio.TimeoutError) as e:
                if sp is not None:
                    sp.mark_error(e)
                self.conn_state.remove_pending(peer.peer_id, h)
                # Connectivity failure (refused / at-capacity / timeout),
                # not misbehavior: short soft cool-off so a flash crowd
                # retries the seeder within seconds once churn frees its
                # slots.
                self.conn_state.blacklist.add(peer.peer_id, h, soft=True)
                if not isinstance(e, PeerBusyError):
                    # Dead addr (refused/timeout), not at-capacity: drop
                    # it from the gossip book so we stop advertising --
                    # and persisting -- an address nobody answers at.
                    # The tracker re-adds it if it comes back.
                    ctl.known_peers.discard(peer.peer_id)
                return
            except WireError as e:
                if sp is not None:
                    sp.mark_error(e)
                self.conn_state.remove_pending(peer.peer_id, h)
                # Garbage handshake = misbehavior: exponential backoff.
                self.conn_state.blacklist.add(peer.peer_id, h)
                return
            # The handshaked identity wins over the (possibly stale)
            # announced one: release the announced pending slot before
            # promoting, or a restarted peer with a new id would leak
            # pending slots forever.
            self.conn_state.remove_pending(peer.peer_id, h)
            if not self.conn_state.promote(theirs.peer_id, h):
                writer.close()
                return
            if self._try_leech_handoff(ctl, reader, writer, theirs):
                return
            self._adopt(ctl, reader, writer, theirs)

    # -- inbound conns -----------------------------------------------------

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            theirs = await handshake_inbound(
                reader, writer, self.peer_id, self._bitfield_for,
                own_listen_port=self.port,
            )
        except _AtCapacity:
            # Polite rejection: the dialer must learn this is capacity,
            # not misbehavior, so it soft-blacklists and retries soon.
            with contextlib.suppress(Exception):
                await send_message(writer, Message.error("busy"))
            writer.close()
            return
        except (OSError, WireError, KeyError, asyncio.TimeoutError):
            writer.close()
            return
        h = theirs.info_hash
        ctl = self._controls.get(h)
        if ctl is None or not self.conn_state.promote(theirs.peer_id, h):
            writer.close()
            return
        if self._try_handoff(ctl, reader, writer, theirs):
            return
        if self._try_leech_handoff(ctl, reader, writer, theirs):
            return
        self._adopt(ctl, reader, writer, theirs)

    def _try_handoff(
        self,
        ctl: _TorrentControl,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        theirs: HandshakeResult,
    ) -> bool:
        """Classify + ship a seed-only inbound conn to a worker shard.

        Seed-only means OUR torrent is complete: this conn will never
        request a piece, never touch the verifier or bufpool -- it only
        serves, which is exactly the half of the data plane the worker
        processes own (p2p/shardpool.py). Leech conns (we still need
        pieces) and bandwidth-shaped nodes (the egress token bucket is
        in-process state a worker cannot share) stay on the main loop.
        Returns False to fall through to the normal in-loop adopt; the
        conn-state slot reserved by promote() travels with the conn and
        is released by the worker's closed verdict.
        """
        pool = self._shardpool
        if pool is None or not pool.can_accept:
            return False
        if not ctl.torrent.complete() or self.bandwidth is not None:
            return False
        if getattr(ctl.torrent, "spool_backed", False):
            # Serve-while-ingest: the backing file is the upload spool; a
            # failed commit unlinks it, which must also close the serving
            # fd -- keep the conn on the main loop until promoted.
            return False
        if not os.path.exists(ctl.torrent.blob_path):
            # Chunk-backed blob (store/chunkstore.py): there is no flat
            # file for the worker's long-lived sendfile fd. Serve from
            # the main loop, whose piece reads compose through the
            # chunk tier -- correctness over the shard fast path.
            return False
        transport = writer.transport
        sock = transport.get_extra_info("socket")
        if sock is None:
            return False  # exotic transport (tests' mocks): keep in-loop
        h = ctl.torrent.info_hash
        try:
            transport.pause_reading()
        except (RuntimeError, NotImplementedError):
            return False
        # Frames the remote pipelined behind its handshake already sit in
        # the parent's StreamReader; they must travel with the fd or the
        # worker would start mid-stream.
        residual = bytes(getattr(reader, "_buffer", b""))
        desc = {
            "peer": theirs.peer_id.hex,
            "ih": h.hex,
            "name": ctl.torrent.metainfo.digest.hex,
            "plen": ctl.torrent.metainfo.piece_length,
            "len": ctl.torrent.metainfo.length,
            "np": ctl.torrent.num_pieces,
            "path": ctl.torrent.blob_path,
            "residual": residual,
            # The dialer's trace context rides the handoff: the worker's
            # serve spans join the leecher's trace even though they run
            # in a forked process (spans ship home over this channel).
            "tp": theirs.traceparent,
        }
        try:
            dup = sock.dup()
        except OSError:
            transport.resume_reading()
            return False
        try:
            ok = pool.try_handoff(dup.fileno(), desc)
        finally:
            # send_fds installed a kernel-held reference in the control
            # message; on failure this dup is simply dropped.
            dup.close()
        if not ok:
            transport.resume_reading()
            return False
        # The worker owns the conn now: retire the parent-side transport
        # WITHOUT closing the connection (the in-flight SCM_RIGHTS ref
        # keeps it alive until the worker adopts the fd).
        transport.abort()
        self.events.emit(
            "add_active_conn", h.hex, peer=theirs.peer_id.hex, shard=True
        )
        return True

    def _try_leech_handoff(
        self,
        ctl: _TorrentControl,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        theirs: HandshakeResult,
    ) -> bool:
        """Classify + ship an active-DOWNLOAD conn to a leech worker
        shard (dialed or accepted while our torrent is still partial).

        The worker owns the socket: recv pump, frame parse, in-process
        serves of pieces we already have, and -- after the parent's
        batched verify -- the pwrite. The parent keeps everything that
        needs shared state: a :class:`LeechConnProxy` is adopted into
        the dispatcher exactly like a Conn, so piece selection, endgame,
        churn, blacklist verdicts, and PEX all run unchanged. Returns
        False to fall through to the normal in-loop adopt.
        """
        pool = self._leech_pool
        if pool is None or not pool.can_accept:
            return False
        torrent = ctl.torrent
        if torrent.complete():
            return False  # nothing left to pull: that's the seed plane
        if self.bandwidth is not None:
            # The ingress token bucket is in-process state a worker
            # cannot share: shaped nodes keep downloads on the main loop.
            return False
        if getattr(torrent, "spool_backed", False):
            return False
        if min(
            torrent.metainfo.piece_length, torrent.metainfo.length
        ) > pool.slot_bytes:
            # The largest ACTUAL piece must fit one ring slot (mmap'd
            # pre-fork, fixed size): longer pieces stay on the main
            # loop. min() because a blob shorter than the nominal piece
            # length has a single piece of its own size.
            return False
        if not os.path.exists(torrent.blob_path):
            # The preallocated .part is the worker's pwrite target; no
            # flat file, no remote writes.
            return False
        transport = writer.transport
        sock = transport.get_extra_info("socket")
        if sock is None:
            return False  # exotic transport (tests' mocks): keep in-loop
        peername = writer.get_extra_info("peername")
        h = torrent.info_hash
        try:
            transport.pause_reading()
        except (RuntimeError, NotImplementedError):
            return False
        residual = bytes(getattr(reader, "_buffer", b""))
        desc = {
            "peer": theirs.peer_id.hex,
            "ih": h.hex,
            "name": torrent.metainfo.digest.hex,
            "plen": torrent.metainfo.piece_length,
            "len": torrent.metainfo.length,
            "np": torrent.num_pieces,
            "path": torrent.blob_path,
            "residual": residual,
            "tp": theirs.traceparent,
            # Leech extensions: open the blob r+ (verdict pwrites land
            # there) and seed the worker's have-set from our bitfield so
            # it can answer the remote's requests in-process.
            "leech": True,
            "wr": True,
            "have": torrent.bitfield(),
        }
        proxy = LeechConnProxy(
            theirs.peer_id, h,
            send_frames=lambda frames: pool.send_frames(proxy, frames),
            close_remote=lambda reason, mis: pool.close_remote(
                proxy, reason, mis
            ),
        )
        try:
            dup = sock.dup()
        except OSError:
            transport.resume_reading()
            return False
        try:
            ok = pool.try_handoff(dup.fileno(), desc, proxy=proxy)
        finally:
            dup.close()
        if not ok:
            transport.resume_reading()
            return False
        # The worker owns the socket now: retire the parent transport
        # without closing the connection (the SCM_RIGHTS ref keeps it
        # alive until the worker adopts the fd).
        transport.abort()
        proxy.start()
        if not ctl.dispatcher.add_conn(
            proxy, theirs.bitfield, theirs.num_pieces
        ):
            # Duplicate peer / bad bitfield: the dispatcher closed the
            # proxy, which echoed the close to the worker. The conn is
            # fully handled -- do NOT fall through to _adopt (the socket
            # is gone from this process).
            self.conn_state.remove(theirs.peer_id, h)
            return True
        key = (theirs.peer_id, h)
        self._conn_owners[key] = proxy
        proxy.closed.add_done_callback(
            lambda _f: self._conn_closed(key, proxy)
        )
        if theirs.listen_port and peername:
            ctl.known_peers.add(
                PeerInfo(theirs.peer_id, peername[0], theirs.listen_port),
                "conn",
            )
        self.events.emit(
            "add_active_conn", h.hex, peer=theirs.peer_id.hex, leech=True
        )
        return True

    def _shard_conn_closed(self, desc: dict, reason: str,
                           misbehavior: bool) -> None:
        """A worker shard reported one of its conns closed: release the
        conn-state slot the handoff carried, and feed misbehavior
        verdicts into the same blacklist path main-loop conns use."""
        peer = PeerID(desc["peer"])
        h = InfoHash(desc["ih"])
        if misbehavior:
            self._peer_failed(peer, h, f"shard conn misbehavior: {reason}")
        else:
            self.conn_state.remove(peer, h)
        self.events.emit(
            "drop_active_conn", h.hex, peer=peer.hex, reason=reason,
            detail="shard",
        )

    def _bitfield_for(self, hs: HandshakeResult) -> tuple[bytes, int]:
        """Inbound handshake: find or create local state for the torrent.

        Origins lazily create seeding controls for any stored blob (the
        resolver loads its metainfo); agents only serve torrents they have
        live controls for. Raising KeyError rejects the conn.
        """
        if self.lameduck:
            # Draining: the polite busy frame -- the dialer soft-
            # blacklists (capacity, not misbehavior) and retries another
            # peer, which is exactly what 503+Retry-After means in HTTP.
            raise _AtCapacity(hs.info_hash.hex)
        if self.conn_state.at_capacity(hs.info_hash):
            raise _AtCapacity(hs.info_hash.hex)
        ctl = self._controls.get(hs.info_hash)
        if ctl is None:
            if self._metainfo_resolver is None:
                raise KeyError(hs.info_hash.hex)
            metainfo = self._metainfo_resolver(hs.name, hs.namespace)
            if metainfo is None or metainfo.info_hash != hs.info_hash:
                raise KeyError(hs.info_hash.hex)
            try:
                ctl = self._get_or_create_control(metainfo, hs.namespace)
            except RuntimeError:
                # stop() swept the controls while this handshake was in
                # flight: reject the conn (the KeyError contract above),
                # don't crash the acceptor and strand the peer's socket.
                raise KeyError(hs.info_hash.hex) from None
        return ctl.torrent.bitfield(), ctl.torrent.num_pieces

    def _adopt(
        self,
        ctl: _TorrentControl,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        theirs: HandshakeResult,
    ) -> None:
        h = ctl.torrent.info_hash
        conn = Conn(
            reader, writer, theirs.peer_id, h,
            bandwidth=self.bandwidth,
            pool=self._bufpool,
            send_batch=self.config.wire_send_batch,
            # The handshaken metainfo's piece length bounds every payload
            # this conn may legally carry -- anything longer is rejected
            # before buffering and blacklists the sender.
            max_payload_length=ctl.torrent.metainfo.piece_length,
        )
        conn.start()
        if not ctl.dispatcher.add_conn(conn, theirs.bitfield, theirs.num_pieces):
            # Rejected (duplicate peer / bad bitfield); the dispatcher closed
            # it. promote() only succeeds when no active slot exists, so the
            # slot being released here is this conn's own.
            self.conn_state.remove(theirs.peer_id, h)
            return
        key = (theirs.peer_id, h)
        self._conn_owners[key] = conn
        conn.closed.add_done_callback(lambda _f: self._conn_closed(key, conn))
        if theirs.listen_port:
            # A live handshake is the best peer record there is: the
            # remote told us its LISTEN port (its transport port here may
            # be an ephemeral dial-side port), and the socket names its
            # reachable ip. Feeds the gossip book + peercache.
            peername = writer.get_extra_info("peername")
            if peername:
                ctl.known_peers.add(
                    PeerInfo(
                        theirs.peer_id, peername[0], theirs.listen_port
                    ),
                    "conn",
                )
        self.events.emit("add_active_conn", h.hex, peer=theirs.peer_id.hex)

    def _conn_closed(self, key: tuple[PeerID, InfoHash], conn: Conn) -> None:
        if self._conn_owners.get(key) is conn:
            del self._conn_owners[key]
            self._pex.forget_conn(key)
            self.conn_state.remove(*key)
            self.events.emit(
                "drop_active_conn", key[1].hex, peer=key[0].hex,
                reason=conn.close_reason or "",
                detail=conn.close_detail,
            )

    # -- retry timer -------------------------------------------------------

    async def _retry_loop(self, ctl: _TorrentControl) -> None:
        while True:
            await asyncio.sleep(self.config.retry_tick)
            with contextlib.suppress(Exception):
                await ctl.dispatcher.tick()

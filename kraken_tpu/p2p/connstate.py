"""Connection bookkeeping: pending/active limits and the peer blacklist.

Mirrors uber/kraken ``lib/torrent/scheduler/connstate`` (global and
per-torrent ``MaxOpenConnectionsPerTorrent`` limits; blacklist with
expiry/backoff quarantining bad peers) -- upstream path, unverified;
SURVEY.md SS2.2/SS5.
"""

from __future__ import annotations

import dataclasses
import time

from kraken_tpu.core.metainfo import InfoHash
from kraken_tpu.core.peer import PeerID
from kraken_tpu.utils.backoff import Backoff


@dataclasses.dataclass
class ConnStateConfig:
    max_open_conns_per_torrent: int = 10
    max_global_conns: int = 1000
    blacklist_expiry_seconds: float = 30.0
    soft_blacklist_seconds: float = 2.0  # connectivity cool-off (no escalation)
    blacklist_backoff: Backoff = dataclasses.field(
        default_factory=lambda: Backoff(
            base_seconds=30.0, factor=2.0, max_seconds=600.0, jitter=0.1
        )
    )

    @classmethod
    def from_dict(cls, doc: dict) -> "ConnStateConfig":
        """YAML shape: the dataclass fields by name (unknown keys
        rejected); ``blacklist_backoff`` may be a nested dict of Backoff
        fields -- coerced here so a bad value fails at config load, not at
        the first blacklist add."""
        doc = dict(doc)
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - fields
        if unknown:
            raise ValueError(f"unknown conn_state config keys: {sorted(unknown)}")
        backoff = doc.get("blacklist_backoff")
        if isinstance(backoff, dict):
            doc["blacklist_backoff"] = Backoff(**backoff)
        return cls(**doc)


class Blacklist:
    """Peers that misbehaved (bad pieces, handshake errors, conn churn);
    entries expire with exponential backoff on repeat offenses."""

    # Expunge cadence: every N adds, sweep entries long past expiry.
    # Amortized O(1) per add; keeps the map bounded on a long-lived node
    # churning torrents forever (the soak harness's leak audit caught
    # the append-only original -- every soft-blacklisted dial to a busy
    # seeder stayed resident for the process lifetime).
    _EXPUNGE_EVERY = 256
    # Entries linger this many multiples of max backoff past expiry so a
    # repeat offender re-appearing shortly after its ban still escalates
    # instead of starting fresh.
    _EXPUNGE_GRACE_FACTOR = 2.0

    def __init__(self, config: ConnStateConfig):
        self._config = config
        # (peer, info_hash) -> (until_ts, offense_count)
        self._entries: dict[tuple[PeerID, InfoHash], tuple[float, int]] = {}
        self._adds_since_expunge = 0

    def _maybe_expunge(self, now: float) -> None:
        self._adds_since_expunge += 1
        if self._adds_since_expunge < self._EXPUNGE_EVERY:
            return
        self._adds_since_expunge = 0
        grace = (
            self._config.blacklist_backoff.max_seconds
            * self._EXPUNGE_GRACE_FACTOR
        )
        for key, (until, _count) in list(self._entries.items()):
            if now - until > grace:
                del self._entries[key]

    def add(
        self, peer: PeerID, h: InfoHash, now: float | None = None,
        soft: bool = False,
    ) -> None:
        """``soft`` = connectivity failure (dial refused, peer at capacity):
        short fixed cool-off, no offense escalation. A flash crowd that hits
        a full seeder must retry within seconds, not back off for minutes
        like a peer that served corrupt pieces."""
        now = time.monotonic() if now is None else now
        self._maybe_expunge(now)
        _until, count = self._entries.get((peer, h), (0.0, 0))
        if soft:
            delay = self._config.soft_blacklist_seconds
            self._entries[(peer, h)] = (max(_until, now + delay), count)
        else:
            delay = self._config.blacklist_backoff.delay(count)
            self._entries[(peer, h)] = (now + delay, count + 1)

    def blocked(self, peer: PeerID, h: InfoHash, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        entry = self._entries.get((peer, h))
        return entry is not None and now < entry[0]

    def reconfigure(self, config: ConnStateConfig) -> None:
        """Live swap: existing entries keep their expiry; future offenses
        use the new backoff/expiry values."""
        self._config = config


class ConnState:
    """Tracks pending (dialing/handshaking) and active conns per torrent."""

    def __init__(self, config: ConnStateConfig | None = None):
        self.config = config or ConnStateConfig()
        self.blacklist = Blacklist(self.config)
        self._pending: dict[InfoHash, set[PeerID]] = {}
        self._active: dict[InfoHash, set[PeerID]] = {}

    def reconfigure(self, config: ConnStateConfig) -> None:
        """Live limit swap: caps apply to the next admission decision;
        existing conns are not torn down (churn/eviction shrinks toward
        new caps naturally). Blacklist entries keep their current expiry."""
        self.config = config
        self.blacklist.reconfigure(config)

    def _count_global(self) -> int:
        return sum(len(s) for s in self._pending.values()) + sum(
            len(s) for s in self._active.values()
        )

    def active_peers(self, h: InfoHash) -> set[PeerID]:
        return set(self._active.get(h, ()))

    def num_active(self, h: InfoHash) -> int:
        return len(self._active.get(h, ()))

    def can_dial(self, peer: PeerID, h: InfoHash) -> bool:
        if self.blacklist.blocked(peer, h):
            return False
        if peer in self._pending.get(h, ()) or peer in self._active.get(h, ()):
            return False
        per_torrent = len(self._pending.get(h, ())) + len(self._active.get(h, ()))
        if per_torrent >= self.config.max_open_conns_per_torrent:
            return False
        return self._count_global() < self.config.max_global_conns

    def at_capacity(self, h: InfoHash) -> bool:
        """Inbound-side check: no slot for another conn on this torrent
        (the accept path rejects POLITELY with a busy frame so the dialer
        soft-blacklists instead of escalating)."""
        per_torrent = len(self._pending.get(h, ())) + len(self._active.get(h, ()))
        return (
            per_torrent >= self.config.max_open_conns_per_torrent
            or self._count_global() >= self.config.max_global_conns
        )

    def add_pending(self, peer: PeerID, h: InfoHash) -> bool:
        if not self.can_dial(peer, h):
            return False
        self._pending.setdefault(h, set()).add(peer)
        return True

    def promote(self, peer: PeerID, h: InfoHash) -> bool:
        """Pending -> active on handshake success. Incoming conns (never
        pending) promote directly if capacity allows."""
        self._pending.get(h, set()).discard(peer)
        if peer in self._active.get(h, ()):
            return False
        active = self._active.setdefault(h, set())
        per_torrent = len(active) + len(self._pending.get(h, ()))
        if per_torrent >= self.config.max_open_conns_per_torrent:
            return False
        active.add(peer)
        return True

    def remove(self, peer: PeerID, h: InfoHash) -> None:
        self._pending.get(h, set()).discard(peer)
        self._active.get(h, set()).discard(peer)

    def remove_pending(self, peer: PeerID, h: InfoHash) -> None:
        """Release only a dial reservation. Dial-path cleanup must use this,
        not ``remove``: the same peer may have promoted a concurrent inbound
        conn to active, and that slot belongs to the live conn."""
        self._pending.get(h, set()).discard(peer)

    def clear_torrent(self, h: InfoHash) -> None:
        self._pending.pop(h, None)
        self._active.pop(h, None)
        # Blacklist rows deliberately survive the torrent: the same
        # blob re-pulled after eviction has the SAME info_hash, so a
        # corrupt peer's escalating verdict must greet the re-pull, not
        # reset with every eviction cycle. Boundedness comes from the
        # amortized expired-entry expunge above, which keeps escalation
        # memory for the grace window and no longer.

"""One peer connection: handshake + framed message pump with bandwidth caps.

Mirrors uber/kraken ``lib/torrent/scheduler/conn`` (handshaker exchanging
peer id / info hash / namespace / bitfield; reader+writer goroutines with
per-conn channels; bandwidth accounting) -- upstream path, unverified;
SURVEY.md SS2.2. Reader/writer goroutines become asyncio tasks; channels
become bounded asyncio queues.

Round-7 fast path: ``send``/``recv`` used to build two ``ensure_future``s
plus an ``asyncio.wait`` set per message -- per-frame event-loop work the
round-5 residual decomposition billed to "dispatcher machinery". Both now
take a non-blocking ``put_nowait``/``get_nowait`` fast path and fall back
to the race-against-``closed`` slow path only when the queue would
actually block. The send loop drains every queued frame into ONE corked
:func:`~kraken_tpu.p2p.wire.send_messages` batch (one ``drain()`` per
batch -- control frames piggyback on payload batches for free), and the
recv loop hands PIECE_PAYLOAD frames straight to the dispatcher's
``payload_handler`` callback, bypassing the recv queue for the hot type.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Callable, Optional

from kraken_tpu.core.metainfo import InfoHash
from kraken_tpu.core.peer import PeerID
from kraken_tpu.p2p.wire import (
    MAX_PAYLOAD,
    Message,
    MsgType,
    PayloadOversizeError,
    WireError,
    recv_message,
    send_message,
    send_messages,
)
from kraken_tpu.utils import failpoints, trace
from kraken_tpu.utils.bandwidth import BandwidthLimiter
from kraken_tpu.utils.bufpool import BufferPool

_SEND_QUEUE = 256
_RECV_QUEUE = 256


class ConnClosedError(Exception):
    pass


@dataclasses.dataclass
class HandshakeResult:
    peer_id: PeerID
    info_hash: InfoHash
    name: str  # blob digest hex
    namespace: str
    bitfield: bytes
    num_pieces: int
    # The dialer's traceparent (utils/trace.py), "" when it had no
    # active trace: serve spans on the accept side join this trace, and
    # it travels with the shardpool handoff descriptor.
    traceparent: str = ""
    # The remote's p2p LISTEN port (0 = unknown/older peer). An inbound
    # conn's transport port is ephemeral, so without this the accept side
    # has no dialable addr to gossip for the peer -- PEX carries only
    # peers whose listen port is known.
    listen_port: int = 0


class Conn:
    """A live, handshaken connection. Use :meth:`start` to spin the pumps.

    Outbound messages go through :meth:`send` (bounded queue, backpressure);
    inbound arrive on :meth:`recv` -- except PIECE_PAYLOAD frames, which a
    registered ``payload_handler`` receives synchronously from the recv
    loop. Either side closing or a wire error closes the conn; ``closed``
    future resolves for cleanup hooks, with the terminal cause recorded on
    ``close_reason`` (and counted on ``conn_closed_total{reason}``) so a
    dying conn is never silent.
    """

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        peer_id: PeerID,
        info_hash: InfoHash,
        bandwidth: BandwidthLimiter | None = None,
        pool: BufferPool | None = None,
        send_batch: int = 16,
        max_payload_length: int = MAX_PAYLOAD,
    ):
        self._reader = reader
        self._writer = writer
        self.peer_id = peer_id
        self.info_hash = info_hash
        self._bw = bandwidth
        self._pool = pool
        self._send_batch = max(1, send_batch)
        # The handshaken torrent's piece length: the tightest honest bound
        # on any PIECE_PAYLOAD this conn may carry. A frame beyond it is
        # rejected BEFORE buffering (a bad peer must not balloon RSS) and
        # marks the conn as misbehaving for the blacklist.
        self._max_payload = max_payload_length
        self._send_q: asyncio.Queue[Optional[Message]] = asyncio.Queue(_SEND_QUEUE)
        self._recv_q: asyncio.Queue[Optional[Message]] = asyncio.Queue(_RECV_QUEUE)
        self._tasks: list[asyncio.Task] = []
        # Created lazily on a RUNNING loop: asyncio.get_event_loop() in
        # __init__ is deprecated and breaks under a non-running loop on
        # 3.12+ (and could bind the future to the wrong loop).
        self._closed_fut: Optional[asyncio.Future] = None
        self.close_reason: Optional[str] = None
        self.close_detail: str = ""
        self.misbehavior = False
        # Dispatcher fast path: sync callable fed PIECE_PAYLOAD messages
        # straight from the recv loop (must not await).
        self.payload_handler: Optional[Callable[[Message], None]] = None
        # piece-traffic counters (network events / metrics)
        self.bytes_sent = 0
        self.bytes_received = 0

    @property
    def closed(self) -> asyncio.Future:
        if self._closed_fut is None:
            self._closed_fut = asyncio.get_running_loop().create_future()
        return self._closed_fut

    def start(self) -> None:
        self.closed  # materialize on the pumps' loop
        self._tasks = [
            asyncio.create_task(self._send_loop()),
            asyncio.create_task(self._recv_loop()),
        ]

    def set_payload_handler(self, handler: Callable[[Message], None]) -> None:
        self.payload_handler = handler

    async def send(self, msg: Message) -> None:
        """Enqueue with backpressure; a conn closing mid-wait unblocks the
        caller with :class:`ConnClosedError` instead of stranding it on a
        full queue. Fast path: when the queue has room, a plain
        ``put_nowait`` -- no futures, no wait set."""
        if self._closed_fut is not None and self._closed_fut.done():
            raise ConnClosedError(str(self.peer_id))
        try:
            self._send_q.put_nowait(msg)
            return
        except asyncio.QueueFull:
            pass
        put = asyncio.ensure_future(self._send_q.put(msg))
        try:
            done, _pending = await asyncio.wait(
                {put, self.closed}, return_when=asyncio.FIRST_COMPLETED
            )
        except asyncio.CancelledError:
            # The CALLER was cancelled mid-wait (teardown, hedge loser):
            # asyncio.wait never cancels its awaitables, so the helper
            # future must be reaped here or it outlives the conn as a
            # forever-pending Queue.put task.
            put.cancel()
            raise
        if put not in done:
            put.cancel()
            raise ConnClosedError(str(self.peer_id))
        await put  # surface put errors, if any

    async def recv(self) -> Message:
        try:
            msg = self._recv_q.get_nowait()
        except asyncio.QueueEmpty:
            if self._closed_fut is not None and self._closed_fut.done():
                raise ConnClosedError(str(self.peer_id))
            get = asyncio.ensure_future(self._recv_q.get())
            try:
                done, _pending = await asyncio.wait(
                    {get, self.closed}, return_when=asyncio.FIRST_COMPLETED
                )
            except asyncio.CancelledError:
                # Caller cancelled mid-wait: reap the helper (see send).
                get.cancel()
                raise
            if get not in done:
                get.cancel()
                raise ConnClosedError(str(self.peer_id))
            msg = await get
        if msg is None:
            raise ConnClosedError(str(self.peer_id))
        return msg

    async def _send_loop(self) -> None:
        reason, detail = "send_loop_exit", ""
        try:
            while True:
                msg = await self._send_q.get()
                stop = msg is None
                batch: list[Message] = [] if stop else [msg]
                # Cork: drain whatever else is already queued (bounded by
                # send_batch) into one vectored write + one drain().
                while not stop and len(batch) < self._send_batch:
                    try:
                        m = self._send_q.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if m is None:
                        stop = True
                        break
                    batch.append(m)
                if batch:
                    payload_bytes = sum(
                        len(m.payload) for m in batch
                        if m.type == MsgType.PIECE_PAYLOAD
                    )
                    if self._bw and payload_bytes:
                        await self._bw.send(payload_bytes)
                    # Failpoint p2p.conn.send.delay: stall this batch (a
                    # congested/slow link) -- drives churn-exemption and
                    # adaptive piece-timeout paths. Evaluated once per
                    # frame so every:N / times:N specs keep frame
                    # semantics.
                    for _m in batch:
                        hit = failpoints.fire("p2p.conn.send.delay")
                        if hit:
                            await asyncio.sleep(hit.delay_s)
                    await send_messages(self._writer, batch)
                    self.bytes_sent += sum(len(m.payload) for m in batch)
                if stop:
                    return
        except ConnectionError as e:
            reason, detail = "connection_error", str(e)
        except WireError as e:
            reason, detail = "wire_error", str(e)
        except asyncio.CancelledError:
            reason = "cancelled"
        finally:
            self.close(reason=reason, detail=detail)

    async def _recv_loop(self) -> None:
        reason, detail = "recv_loop_exit", ""
        misbehavior = False
        pending: Optional[Message] = None  # read but not yet handed off
        try:
            while True:
                pending = None
                msg = pending = await recv_message(
                    self._reader, pool=self._pool, max_payload=self._max_payload
                )
                if msg.type == MsgType.PIECE_PAYLOAD:
                    if self._bw:
                        await self._bw.recv(len(msg.payload))
                    self.bytes_received += len(msg.payload)
                    if msg.payload:
                        # Failpoint p2p.conn.recv.corrupt: flip the first
                        # payload byte -- the exact fault a bad NIC/disk on
                        # the remote produces. Verify must catch it, the
                        # dispatcher must ban the peer, the pull must finish
                        # from healthy peers. On the pooled path this
                        # mutates the leased buffer IN PLACE.
                        if failpoints.fire("p2p.conn.recv.corrupt"):
                            pl = msg.payload
                            if isinstance(pl, memoryview):
                                pl[0] ^= 0xFF
                            else:
                                msg.payload = bytes([pl[0] ^ 0xFF]) + pl[1:]
                        # Failpoint p2p.conn.disconnect: drop the conn mid-
                        # transfer, discarding this frame (remote crash /
                        # RST) -- re-announce + re-request must recover.
                        if failpoints.fire("p2p.conn.disconnect"):
                            msg.release()
                            raise ConnectionResetError(
                                "failpoint p2p.conn.disconnect"
                            )
                    if self.payload_handler is not None:
                        # Hot-type bypass: no queue put, no pump wakeup.
                        pending = None  # ownership moves to the handler
                        self.payload_handler(msg)
                        continue
                else:
                    self.bytes_received += len(msg.payload)
                await self._recv_q.put(msg)
                pending = None  # queue drained by close() or a consumer
        except PayloadOversizeError as e:
            reason, detail, misbehavior = "oversize_payload", str(e), True
        except ConnectionError as e:
            reason, detail = "connection_error", str(e)
        except WireError as e:
            reason, detail = "wire_error", str(e)
        except asyncio.CancelledError:
            reason = "cancelled"
        finally:
            # A frame read but never handed off (cancelled mid-put, bw
            # wait, failpoint) must still return its pooled buffer.
            if pending is not None:
                pending.release()
            self.close(reason=reason, detail=detail, misbehavior=misbehavior)

    def close(
        self,
        reason: str = "local_close",
        detail: str = "",
        misbehavior: bool = False,
    ) -> None:
        if misbehavior:
            self.misbehavior = True
        if self.close_reason is not None:
            return  # first close wins; the pumps' finally re-enter here
        self.close_reason = reason
        self.close_detail = detail
        from kraken_tpu.utils.metrics import REGISTRY

        REGISTRY.counter(
            "conn_closed_total", "P2P conns closed, by terminal cause"
        ).inc(reason=reason)
        fut = self._closed_fut
        if fut is None:
            try:
                fut = self.closed
            except RuntimeError:
                fut = None  # no loop ever ran this conn: nothing to wake
        if fut is not None and not fut.done():
            # The resolved future unblocks every send()/recv() waiter (they
            # race against it); no sentinel bookkeeping needed.
            fut.set_result(None)
        self._writer.close()
        for t in self._tasks:
            t.cancel()
        # Messages parked in the recv queue die with the conn: return
        # their pooled buffers (the leak detector counts every lease).
        while True:
            try:
                queued = self._recv_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if queued is not None:
                queued.release()

    async def wait_closed(self) -> None:
        await asyncio.shield(self.closed)


class LeechConnProxy:
    """Main-loop stand-in for a download conn whose SOCKET lives in a
    forked leech worker (p2p/shardpool.py).

    The dispatcher talks to it exactly like a :class:`Conn` -- same
    ``send``/``recv``/``close``/``closed`` surface, same misbehavior and
    ``close_reason`` contract -- but there are no pumps here: the worker
    runs recv + frame parse off the main loop and the shardpool's
    control-channel reader feeds this proxy via the ``on_*`` hooks.
    Outbound frames (piece requests, announce fanout, PEX) are packed
    and shipped to the worker, which writes them to the real socket.
    PIECE_PAYLOAD arrivals come back as shared-memory-ring Messages via
    :meth:`deliver_payload`, so the payload bytes never transit the
    control channel.

    The callables are injected (rather than holding a pool reference)
    so this module never imports shardpool: ``send_frames`` takes
    ``[(mtype, header_dict, payload_bytes), ...]`` and ``close_remote``
    takes ``(reason, misbehavior)`` -- both sync and best-effort, like
    every control-channel send.
    """

    def __init__(
        self,
        peer_id: PeerID,
        info_hash: InfoHash,
        *,
        send_frames: Callable[[list], None],
        close_remote: Callable[[str, bool], None],
    ):
        self.peer_id = peer_id
        self.info_hash = info_hash
        self._send_frames = send_frames
        self._close_remote = close_remote
        self._recv_q: asyncio.Queue[Optional[Message]] = asyncio.Queue(_RECV_QUEUE)
        self._closed_fut: Optional[asyncio.Future] = None
        self.close_reason: Optional[str] = None
        self.close_detail: str = ""
        self.misbehavior = False
        self.payload_handler: Optional[Callable[[Message], None]] = None
        self.bytes_sent = 0
        self.bytes_received = 0
        # Set when the WORKER already tore the conn down (closed verdict
        # or worker death): closing then must not echo a close back at a
        # cid the worker no longer knows (or a worker that no longer
        # exists).
        self._remote_gone = False

    @property
    def closed(self) -> asyncio.Future:
        if self._closed_fut is None:
            self._closed_fut = asyncio.get_running_loop().create_future()
        return self._closed_fut

    def start(self) -> None:
        self.closed  # materialize on the dispatcher's loop; pumps are remote

    def set_payload_handler(self, handler: Callable[[Message], None]) -> None:
        self.payload_handler = handler

    async def send(self, msg: Message) -> None:
        if self._closed_fut is not None and self._closed_fut.done():
            raise ConnClosedError(str(self.peer_id))
        payload = msg.payload
        if isinstance(payload, memoryview):
            payload = bytes(payload)
        self._send_frames([(int(msg.type), msg.header, payload)])
        self.bytes_sent += len(payload)

    async def recv(self) -> Message:
        try:
            msg = self._recv_q.get_nowait()
        except asyncio.QueueEmpty:
            if self._closed_fut is not None and self._closed_fut.done():
                raise ConnClosedError(str(self.peer_id))
            get = asyncio.ensure_future(self._recv_q.get())
            try:
                done, _pending = await asyncio.wait(
                    {get, self.closed}, return_when=asyncio.FIRST_COMPLETED
                )
            except asyncio.CancelledError:
                get.cancel()
                raise
            if get not in done:
                get.cancel()
                raise ConnClosedError(str(self.peer_id))
            msg = await get
        if msg is None:
            raise ConnClosedError(str(self.peer_id))
        return msg

    # -- shardpool-facing hooks (control-channel reader, same loop) -----

    def on_frame(self, mtype: int, header: dict, payload: bytes = b"") -> None:
        """A control frame the worker chose to forward (announce /
        bitfield / complete / PEX); ``payload`` carries the small
        inline bytes of a BITFIELD, empty otherwise."""
        if self.close_reason is not None:
            return
        msg = Message(MsgType(mtype), header or {}, payload or b"")
        try:
            self._recv_q.put_nowait(msg)
        except asyncio.QueueFull:
            # The dispatcher pump stopped draining (wedged peer task):
            # same terminal outcome as a Conn whose recv loop died.
            self.close(reason="recv_overflow")

    def deliver_payload(self, msg: Message) -> None:
        """A completed piece: ``msg.payload`` is a view into the shared
        ring, ``msg.lease`` the slot lease (idempotent release, like any
        pooled payload)."""
        if self.close_reason is not None:
            msg.release()
            return
        self.bytes_received += len(msg.payload)
        if self.payload_handler is not None:
            self.payload_handler(msg)
            return
        try:
            self._recv_q.put_nowait(msg)
        except asyncio.QueueFull:
            msg.release()
            self.close(reason="recv_overflow")

    def on_remote_closed(self, reason: str, misbehavior: bool = False) -> None:
        """The worker's side of the conn died first (peer hung up, wire
        error, worker exit): surface it exactly like a local Conn pump
        failing, misbehavior verdict intact so the blacklist escalation
        survives the fork boundary."""
        self._remote_gone = True
        self.close(reason=reason, misbehavior=misbehavior)

    # -------------------------------------------------------------------

    def close(
        self,
        reason: str = "local_close",
        detail: str = "",
        misbehavior: bool = False,
    ) -> None:
        if misbehavior:
            self.misbehavior = True
        if self.close_reason is not None:
            return
        self.close_reason = reason
        self.close_detail = detail
        from kraken_tpu.utils.metrics import REGISTRY

        REGISTRY.counter(
            "conn_closed_total", "P2P conns closed, by terminal cause"
        ).inc(reason=reason)
        fut = self._closed_fut
        if fut is None:
            try:
                fut = self.closed
            except RuntimeError:
                fut = None
        if fut is not None and not fut.done():
            fut.set_result(None)
        if not self._remote_gone:
            self._close_remote(reason, self.misbehavior)
        # Undelivered payloads die with the conn: their slot leases must
        # flow back to the ring or the leak audit trips.
        while True:
            try:
                queued = self._recv_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if queued is not None:
                queued.release()

    async def wait_closed(self) -> None:
        await asyncio.shield(self.closed)


async def handshake_outbound(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    own_peer_id: PeerID,
    info_hash: InfoHash,
    name: str,
    namespace: str,
    own_bitfield: bytes,
    num_pieces: int,
    timeout: float = 10.0,
    own_listen_port: int = 0,
) -> HandshakeResult:
    """Dial-side handshake: send ours, await theirs. The active trace
    context (the dial span) rides the handshake so the remote's serve
    spans join this download's trace."""
    await send_message(
        writer,
        Message.handshake(
            str(own_peer_id), info_hash.hex, name, namespace, own_bitfield,
            num_pieces, traceparent=trace.current_traceparent() or "",
            listen_port=own_listen_port,
        ),
    )
    return await _read_handshake(reader, timeout)


async def handshake_inbound(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    own_peer_id: PeerID,
    own_bitfield_for: "callable",
    timeout: float = 10.0,
    own_listen_port: int = 0,
) -> HandshakeResult:
    """Accept-side handshake: read theirs first (it names the torrent),
    then reply with our bitfield for that torrent.

    ``own_bitfield_for(handshake) -> (bits, num_pieces)`` lets the
    scheduler look up (or create) local torrent state; raising aborts the
    conn.
    """
    theirs = await _read_handshake(reader, timeout)
    bits, num_pieces = own_bitfield_for(theirs)
    await send_message(
        writer,
        Message.handshake(
            str(own_peer_id), theirs.info_hash.hex, theirs.name,
            theirs.namespace, bits, num_pieces,
            listen_port=own_listen_port,
        ),
    )
    return theirs


class PeerBusyError(WireError):
    """The remote rejected the conn for CAPACITY, not misbehavior: callers
    soft-blacklist (short, non-escalating) instead of the exponential
    backoff a garbage handshake earns."""


async def _read_handshake(reader: asyncio.StreamReader, timeout: float) -> HandshakeResult:
    msg = await asyncio.wait_for(recv_message(reader), timeout)
    if msg.type == MsgType.ERROR and msg.header.get("code") == "busy":
        raise PeerBusyError("peer at connection capacity")
    if msg.type != MsgType.HANDSHAKE:
        raise WireError(f"expected HANDSHAKE, got {msg.type.name}")
    h = msg.header
    try:
        return HandshakeResult(
            peer_id=PeerID(h["peer_id"]),
            info_hash=InfoHash(h["info_hash"]),
            name=h["name"],
            namespace=h["namespace"],
            bitfield=msg.payload,
            num_pieces=h["num_pieces"],
            traceparent=str(h.get("tp", "") or ""),
            listen_port=int(h.get("lp", 0) or 0),
        )
    except (KeyError, ValueError) as e:
        raise WireError(f"malformed handshake: {e}") from e

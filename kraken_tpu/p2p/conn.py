"""One peer connection: handshake + framed message pump with bandwidth caps.

Mirrors uber/kraken ``lib/torrent/scheduler/conn`` (handshaker exchanging
peer id / info hash / namespace / bitfield; reader+writer goroutines with
per-conn channels; bandwidth accounting) -- upstream path, unverified;
SURVEY.md SS2.2. Reader/writer goroutines become asyncio tasks; channels
become bounded asyncio queues.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Optional

from kraken_tpu.core.metainfo import InfoHash
from kraken_tpu.core.peer import PeerID
from kraken_tpu.p2p.wire import Message, MsgType, WireError, recv_message, send_message
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.bandwidth import BandwidthLimiter

_SEND_QUEUE = 256
_RECV_QUEUE = 256


class ConnClosedError(Exception):
    pass


@dataclasses.dataclass
class HandshakeResult:
    peer_id: PeerID
    info_hash: InfoHash
    name: str  # blob digest hex
    namespace: str
    bitfield: bytes
    num_pieces: int


class Conn:
    """A live, handshaken connection. Use :meth:`start` to spin the pumps.

    Outbound messages go through :meth:`send` (bounded queue, backpressure);
    inbound arrive on :meth:`recv`. Either side closing or a wire error
    closes the conn; ``closed`` future resolves for cleanup hooks.
    """

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        peer_id: PeerID,
        info_hash: InfoHash,
        bandwidth: BandwidthLimiter | None = None,
    ):
        self._reader = reader
        self._writer = writer
        self.peer_id = peer_id
        self.info_hash = info_hash
        self._bw = bandwidth
        self._send_q: asyncio.Queue[Optional[Message]] = asyncio.Queue(_SEND_QUEUE)
        self._recv_q: asyncio.Queue[Optional[Message]] = asyncio.Queue(_RECV_QUEUE)
        self._tasks: list[asyncio.Task] = []
        self.closed: asyncio.Future[None] = asyncio.get_event_loop().create_future()
        # piece-traffic counters (network events / metrics)
        self.bytes_sent = 0
        self.bytes_received = 0

    def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._send_loop()),
            asyncio.create_task(self._recv_loop()),
        ]

    async def send(self, msg: Message) -> None:
        """Enqueue with backpressure; a conn closing mid-wait unblocks the
        caller with :class:`ConnClosedError` instead of stranding it on a
        full queue."""
        if self.closed.done():
            raise ConnClosedError(str(self.peer_id))
        put = asyncio.ensure_future(self._send_q.put(msg))
        done, _pending = await asyncio.wait(
            {put, self.closed}, return_when=asyncio.FIRST_COMPLETED
        )
        if put not in done:
            put.cancel()
            raise ConnClosedError(str(self.peer_id))
        await put  # surface put errors, if any

    async def recv(self) -> Message:
        get = asyncio.ensure_future(self._recv_q.get())
        done, _pending = await asyncio.wait(
            {get, self.closed}, return_when=asyncio.FIRST_COMPLETED
        )
        if get not in done:
            get.cancel()
            raise ConnClosedError(str(self.peer_id))
        msg = await get
        if msg is None:
            raise ConnClosedError(str(self.peer_id))
        return msg

    async def _send_loop(self) -> None:
        try:
            while True:
                msg = await self._send_q.get()
                if msg is None:
                    return
                if self._bw and msg.type == MsgType.PIECE_PAYLOAD:
                    await self._bw.send(len(msg.payload))
                # Failpoint p2p.conn.send.delay: stall this frame (a
                # congested/slow link) -- drives churn-exemption and
                # adaptive piece-timeout paths.
                hit = failpoints.fire("p2p.conn.send.delay")
                if hit:
                    await asyncio.sleep(hit.delay_s)
                await send_message(self._writer, msg)
                self.bytes_sent += len(msg.payload)
        except (ConnectionError, WireError, asyncio.CancelledError):
            pass
        finally:
            self.close()

    async def _recv_loop(self) -> None:
        try:
            while True:
                msg = await recv_message(self._reader)
                if self._bw and msg.type == MsgType.PIECE_PAYLOAD:
                    await self._bw.recv(len(msg.payload))
                self.bytes_received += len(msg.payload)
                if msg.type == MsgType.PIECE_PAYLOAD and msg.payload:
                    # Failpoint p2p.conn.recv.corrupt: flip the first
                    # payload byte -- the exact fault a bad NIC/disk on
                    # the remote produces. Verify must catch it, the
                    # dispatcher must ban the peer, the pull must finish
                    # from healthy peers.
                    if failpoints.fire("p2p.conn.recv.corrupt"):
                        msg.payload = (
                            bytes([msg.payload[0] ^ 0xFF]) + msg.payload[1:]
                        )
                    # Failpoint p2p.conn.disconnect: drop the conn mid-
                    # transfer, discarding this frame (remote crash /
                    # RST) -- re-announce + re-request must recover.
                    if failpoints.fire("p2p.conn.disconnect"):
                        raise ConnectionResetError(
                            "failpoint p2p.conn.disconnect"
                        )
                await self._recv_q.put(msg)
        except (ConnectionError, WireError, asyncio.CancelledError):
            pass
        finally:
            self.close()

    def close(self) -> None:
        if not self.closed.done():
            # The resolved future unblocks every send()/recv() waiter (they
            # race against it); no sentinel bookkeeping needed.
            self.closed.set_result(None)
            self._writer.close()
            for t in self._tasks:
                t.cancel()

    async def wait_closed(self) -> None:
        await asyncio.shield(self.closed)


async def handshake_outbound(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    own_peer_id: PeerID,
    info_hash: InfoHash,
    name: str,
    namespace: str,
    own_bitfield: bytes,
    num_pieces: int,
    timeout: float = 10.0,
) -> HandshakeResult:
    """Dial-side handshake: send ours, await theirs."""
    await send_message(
        writer,
        Message.handshake(
            str(own_peer_id), info_hash.hex, name, namespace, own_bitfield,
            num_pieces,
        ),
    )
    return await _read_handshake(reader, timeout)


async def handshake_inbound(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    own_peer_id: PeerID,
    own_bitfield_for: "callable",
    timeout: float = 10.0,
) -> HandshakeResult:
    """Accept-side handshake: read theirs first (it names the torrent),
    then reply with our bitfield for that torrent.

    ``own_bitfield_for(handshake) -> (bits, num_pieces)`` lets the
    scheduler look up (or create) local torrent state; raising aborts the
    conn.
    """
    theirs = await _read_handshake(reader, timeout)
    bits, num_pieces = own_bitfield_for(theirs)
    await send_message(
        writer,
        Message.handshake(
            str(own_peer_id), theirs.info_hash.hex, theirs.name,
            theirs.namespace, bits, num_pieces,
        ),
    )
    return theirs


class PeerBusyError(WireError):
    """The remote rejected the conn for CAPACITY, not misbehavior: callers
    soft-blacklist (short, non-escalating) instead of the exponential
    backoff a garbage handshake earns."""


async def _read_handshake(reader: asyncio.StreamReader, timeout: float) -> HandshakeResult:
    msg = await asyncio.wait_for(recv_message(reader), timeout)
    if msg.type == MsgType.ERROR and msg.header.get("code") == "busy":
        raise PeerBusyError("peer at connection capacity")
    if msg.type != MsgType.HANDSHAKE:
        raise WireError(f"expected HANDSHAKE, got {msg.type.name}")
    h = msg.header
    try:
        return HandshakeResult(
            peer_id=PeerID(h["peer_id"]),
            info_hash=InfoHash(h["info_hash"]),
            name=h["name"],
            namespace=h["namespace"],
            bitfield=msg.payload,
            num_pieces=h["num_pieces"],
        )
    except (KeyError, ValueError) as e:
        raise WireError(f"malformed handshake: {e}") from e

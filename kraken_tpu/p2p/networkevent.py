"""Structured swarm tracing: JSONL network events for offline analysis.

Mirrors uber/kraken ``lib/torrent/networkevent`` (every swarm event --
conn open/close, piece request/receive/send, blacklist -- emitted as
structured JSON to a dedicated sink for swarm reconstruction) -- upstream
path, unverified; SURVEY.md SS5.
"""

from __future__ import annotations

import json
import logging
import time
from typing import IO, Optional

from kraken_tpu.utils import trace

_log = logging.getLogger("kraken.networkevent")
_sink_failures = None  # lazy FailureMeter: metrics import cycles at module load


class Name:
    ADD_TORRENT = "add_torrent"
    ADD_ACTIVE_CONN = "add_active_conn"
    DROP_ACTIVE_CONN = "drop_active_conn"
    BLACKLIST_CONN = "blacklist_conn"
    REQUEST_PIECE = "request_piece"
    RECEIVE_PIECE = "receive_piece"
    TORRENT_COMPLETE = "torrent_complete"
    # One structured line per completed download with the operative
    # numbers (pieces, peers used, bytes up/down, duration, blacklist
    # events) -- the reference's per-torrent torrentlog summary, riding
    # the same JSONL stream so offline swarm analysis gets lifecycle
    # rollups without re-deriving them from the piece events.
    TORRENT_SUMMARY = "torrent_summary"
    ANNOUNCE = "announce"


class Producer:
    """Writes one JSON object per line to ``sink`` (a file-like) or, with
    ``sink=None``, keeps an in-memory ring for tests."""

    def __init__(self, peer_id: str, sink: Optional[IO[str]] = None, keep: int = 10000):
        self._peer_id = peer_id
        self._sink = sink
        self._events: list[dict] = []
        self._keep = keep

    def emit(self, name: str, info_hash: str = "", **fields) -> None:
        event = {
            "name": name,
            "ts": time.time(),
            "self": self._peer_id,
            "info_hash": info_hash,
            **fields,
        }
        # Events emitted under an active span carry its trace id, so
        # offline swarm reconstructions (JSONL) join the distributed
        # traces -- the one key that connects the two planes.
        ids = trace.current_ids()
        if ids is not None:
            event["trace_id"] = ids[0]
        if self._sink is not None:
            # Tracing must never affect the data plane: a full disk or a
            # closed sink is an observability failure, not peer
            # misbehavior (an emit raising inside a dispatcher io task
            # would blacklist an innocent peer).
            try:
                self._sink.write(
                    json.dumps(event, separators=(",", ":")) + "\n"
                )
            except Exception as e:
                # ...but a full disk / closed sink must still be SEEN:
                # counted + one throttled WARN, never a per-event flood.
                global _sink_failures
                if _sink_failures is None:
                    from kraken_tpu.utils.metrics import FailureMeter

                    _sink_failures = FailureMeter(
                        "network_event_sink_errors_total",
                        "Network-event JSONL writes that raised (full"
                        " disk / closed sink); events were dropped",
                        _log,
                    )
                _sink_failures.record("network event sink write", e)
        else:
            self._events.append(event)
            if len(self._events) > self._keep:
                del self._events[: -self._keep]

    @property
    def events(self) -> list[dict]:
        return list(self._events)


class NoopProducer(Producer):
    def __init__(self):
        super().__init__("")

    def emit(self, name: str, info_hash: str = "", **fields) -> None:
        pass

"""Torrent storage: piece-addressed views over the CAStore.

Mirrors uber/kraken ``lib/torrent/storage`` (``Torrent`` interface with
``WritePiece``/``GetPieceReader``/``MissingPieces``...; agent archive that
allocates the cache file and persists the piece bitfield for crash-resume;
origin archive seeding completed CAStore blobs) -- upstream paths,
unverified; SURVEY.md SS2.2.

**Piece verification on write lives here** -- the agent-side hot loop the
north star routes through ``PieceHasher``: received pieces are verified by
the :class:`BatchedVerifier`, which coalesces concurrent arrivals into one
batched TPU dispatch (per BASELINE.json's agent-verify config).
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time as _time
from typing import Optional

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import PieceHasher, get_hasher
from kraken_tpu.core.metainfo import MetaInfo
from kraken_tpu.store import CAStore, PieceStatusMetadata


_log = logging.getLogger("kraken.storage")


class PieceError(Exception):
    pass


class BatchedVerifier:
    """Verifies received pieces against their expected digests, batching
    concurrent arrivals into one ``PieceHasher.hash_batch`` dispatch.

    Under swarm load many pieces land within a few ms; each ``verify``
    parks on a future while a single flusher task drains the queue --
    one TPU dispatch per drain instead of one per piece. An idle swarm
    pays only ``max_delay`` extra latency (default 2 ms).
    """

    def __init__(
        self,
        hasher: PieceHasher | None = None,
        max_batch: int = 1024,
        max_delay_seconds: float = 0.0,
    ):
        # max_delay 0 = one event-loop tick: every _on_payload task already
        # scheduled this tick enqueues before the flusher runs, so a burst
        # (pipeline-depth frames landing in one recv buffer) still batches,
        # while a trickle no longer pays a fixed 2 ms per piece -- at
        # 1 MiB pieces that tax alone capped a pair at ~500 MB/s (round-5
        # pair profile). Raise it only to build bigger TPU batches.
        # Public: the agent's scrubber reuses this hasher's pool for its
        # digest work (assembly wiring) -- renaming it must break loudly.
        self.hasher = hasher or get_hasher("cpu")
        self._max_batch = max_batch
        self._max_delay = max_delay_seconds
        self._queue: list[tuple[bytes, bytes, asyncio.Future]] = []
        self._flusher: Optional[asyncio.Task] = None
        self._inflight: set[asyncio.Task] = set()  # strong refs to hash tasks
        # Coalescing observability (cached refs: one flush per batch, but
        # the degenerate batch-of-1 case this exists to expose IS the
        # per-piece path): the size histogram says whether arrivals
        # actually coalesce, and the per-path batch counter splits host
        # SHA from TPU dispatches -- verify_pieces_total /
        # verify_batches_total is the average batch size on a dashboard.
        from kraken_tpu.utils.metrics import REGISTRY

        self._h_batch_size = REGISTRY.histogram(
            "verify_batch_size",
            "Pieces coalesced into each verify flush",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        )
        self._c_batches = REGISTRY.counter(
            "verify_batches_total",
            "Verify flushes dispatched, by hash path (host|tpu)",
        )
        self._path_label = (
            "host" if getattr(self.hasher, "name", "cpu") == "cpu" else "tpu"
        )

    async def verify(self, data: bytes | memoryview, expected: bytes) -> bool:
        # ``data`` may be a pooled memoryview (zero-copy recv path): the
        # caller keeps its lease alive until this returns, and hashlib
        # consumes buffer-protocol objects directly.
        loop = asyncio.get_running_loop()
        fut: asyncio.Future[bool] = loop.create_future()
        self._queue.append((data, expected, fut))
        if self._flusher is None or self._flusher.done():
            self._flusher = asyncio.create_task(self._flush_soon())
        if len(self._queue) >= self._max_batch:
            self._flush_now()
        return await fut

    async def _flush_soon(self) -> None:
        await asyncio.sleep(self._max_delay)
        self._flush_now()

    def _flush_now(self) -> None:
        batch, self._queue = self._queue, []
        if not batch:
            return
        from kraken_tpu.utils.metrics import REGISTRY

        REGISTRY.counter(
            "verify_pieces_total", "Pieces through batched verification"
        ).inc(len(batch))
        REGISTRY.gauge(
            "verify_batch_occupancy",
            "Batch fill of the last verify flush (batched / max_batch)",
        ).set(len(batch) / self._max_batch)
        self._h_batch_size.observe(len(batch))
        self._c_batches.inc(1, path=self._path_label)
        # The hash itself runs OFF the event loop: a full batch is hundreds
        # of MBs (CPU: ~100+ ms; TPU: a blocking device round-trip), and an
        # on-loop hash stalls every conn pump, announce, and accept for the
        # duration. hashlib releases the GIL for large buffers, so the
        # loop genuinely keeps running. Multiple flushes may hash
        # concurrently; each resolves only its own batch's futures, so
        # ordering doesn't matter.
        t = asyncio.create_task(self._hash_off_loop(batch))
        self._inflight.add(t)
        t.add_done_callback(self._inflight.discard)

    async def _hash_off_loop(
        self, batch: list[tuple[bytes, bytes, asyncio.Future]]
    ) -> None:
        # Drop abandoned entries BEFORE touching their buffers: a waiter
        # cancelled mid-verify (torrent teardown, peer drop) releases its
        # pooled payload buffer from the task's done-callback, and the
        # verifier is SHARED across torrents -- hashing a released
        # memoryview would fail the whole batch and blacklist innocent
        # peers of unrelated torrents. A cancelled await marks its future
        # done, so this filter removes exactly the doomed entries.
        batch = [(d, e, f) for d, e, f in batch if not f.done()]
        if not batch:
            return
        try:
            digests = await asyncio.to_thread(
                self.hasher.hash_batch, [d for d, _e, _f in batch]
            )
        except Exception:
            # One bad entry (e.g. a buffer released in the race window
            # between the filter above and the hash) must not fail its
            # batch-mates: retry per item, failing only what individually
            # fails.
            for d, expected, fut in batch:
                if fut.done():
                    continue
                try:
                    got = await asyncio.to_thread(
                        self.hasher.hash_batch, [d]
                    )
                    fut.set_result(bytes(got[0]) == expected)
                except Exception as e:
                    if not fut.done():
                        fut.set_exception(e)
            return
        for (d, expected, fut), got in zip(batch, digests):
            if not fut.done():
                fut.set_result(bytes(got) == expected)


class _FlatIO:
    """Raw-fd IO handle for flat-file torrents: the pread/pwrite/close
    trio :class:`Torrent` ref-counts, shaped exactly like the chunk
    tier's ChunkReader so both storage representations share the piece
    IO path (reads; only flat files ever take writes)."""

    __slots__ = ("_fd",)

    def __init__(self, fd: int):
        self._fd = fd

    def pread(self, n: int, off: int) -> bytes:
        return os.pread(self._fd, n, off)

    def pwrite(self, data, off: int) -> int:
        return os.pwrite(self._fd, data, off)

    def close(self) -> None:
        os.close(self._fd)


class Torrent:
    """Piece-addressed access to one blob in the store.

    Complete torrents (origin seeding) read straight from the committed
    blob. Incomplete torrents own a pre-allocated cache file plus the
    persisted piece bitfield; the final ``write_piece`` completes them.
    """

    def __init__(
        self,
        store: CAStore,
        metainfo: MetaInfo,
        verifier: BatchedVerifier,
        complete: bool = False,
        path: Optional[str] = None,
    ):
        self.store = store
        self.metainfo = metainfo
        self._verifier = verifier
        # Serve-while-ingest: a complete torrent whose bytes still live at
        # the upload spool path (every byte is on disk; commit is just the
        # rename). promote() repoints it at the cache path post-commit --
        # an fd opened on the spool keeps working because rename preserves
        # the inode. While spool_backed, shard handoff is skipped: the
        # worker's long-lived fd would outlive a commit failure's unlink.
        self.spool_backed = False
        if complete:
            if path is not None:
                self._path = path
                self.spool_backed = True
            else:
                self._path = store.cache_path(metainfo.digest)
            self._status = None  # complete: no bitfield needed
        else:
            # Incomplete data lives at the partial path until the last
            # piece lands; only then is it renamed into the cache, so
            # ``in_cache`` can never observe a half-written blob.
            self._path = store.partial_path(metainfo.digest)
            md = store.get_metadata(metainfo.digest, PieceStatusMetadata)
            self._status = md or PieceStatusMetadata(metainfo.num_pieces)
        # Serializes bitfield updates + completion check.
        self._lock = asyncio.Lock()
        self._full_bits: Optional[bytes] = None  # memoized complete bitfield
        # One long-lived fd + os.pread/pwrite replace the per-piece
        # open/seek/read/close of earlier rounds: positional IO is
        # thread-safe (no shared file offset), so piece reads and writes
        # from worker threads need no lock and no file-table churn. The
        # pair-profile (PERF.md round 5) localized ~35% of the wall to
        # exactly this machinery.
        self._fd: Optional[int] = None
        self._fd_lock = threading.Lock()
        self._fd_refs = 0  # in-flight pread/pwrite count (teardown gate)
        self._fd_closed = False
        # Bitfield persistence is DEBOUNCED (the round-5 pair profile's
        # single largest cost was one sidecar rename per piece, on the
        # event loop): pieces mark the bitfield dirty, a per-torrent
        # flusher persists it at most every BITS_FLUSH_SECONDS, and
        # close()/completion flush what remains. Crash window: pieces
        # landed since the last flush are re-downloaded on resume -- the
        # persisted bitfield may UNDERstate progress, never overstate it
        # (bits are set only after their piece's data write returns).
        self._bits_dirty = False
        self._bits_flusher: Optional[asyncio.Task] = None
        # Cumulative per-piece stage walls for the torrent_summary
        # stage split (dispatch.py): how long this torrent's pieces
        # spent parked on verify vs the data write. Pieces pipeline, so
        # these OVERLAP each other and the wire wait -- they sum past
        # the pull's wall clock; they are stage COSTS, not a timeline.
        self.verify_wall = 0.0
        self.write_wall = 0.0

    BITS_FLUSH_SECONDS = 0.2

    # -- introspection -----------------------------------------------------

    @property
    def digest(self) -> Digest:
        return self.metainfo.digest

    @property
    def info_hash(self):
        return self.metainfo.info_hash

    @property
    def num_pieces(self) -> int:
        return self.metainfo.num_pieces

    @property
    def blob_path(self) -> str:
        """Filesystem path of the backing file (the committed cache path
        once complete) -- what the seed-serve worker shards open for
        their long-lived sendfile fd. A chunk-backed blob has NO flat
        path: the scheduler's shard handoff checks existence and keeps
        such conns on the main loop, whose piece reads compose through
        the chunk tier (materialize_flat is the opt-in escape hatch)."""
        return self._path

    def complete(self) -> bool:
        return self._status is None or self._status.complete()

    def has_piece(self, i: int) -> bool:
        return self._status is None or self._status.has(i)

    def missing_pieces(self) -> list[int]:
        return [] if self._status is None else self._status.missing()

    def num_pieces_complete(self) -> int:
        return self.num_pieces if self._status is None else self._status.count()

    def bitfield(self) -> bytes:
        if self._status is None:
            # Memoized: a seeder rebuilds this for EVERY inbound handshake,
            # and O(pieces) per handshake x a full conn budget on a
            # 10k-piece blob is real loop time.
            if self._full_bits is None:
                full = PieceStatusMetadata(self.num_pieces)
                for i in range(self.num_pieces):
                    full.set(i)
                self._full_bits = bytes(full.bits)
            return self._full_bits
        return bytes(self._status.bits)

    # -- pieces ------------------------------------------------------------

    def _open_io(self):
        """The torrent's IO handle: a raw fd on the backing file, or --
        for a COMPLETE blob whose bytes live in the chunk tier -- a
        composed :class:`~kraken_tpu.store.chunkstore.ChunkReader`.
        Both expose ``pread``; only the flat handle can ``pwrite``
        (incomplete torrents always write into a flat ``.part``)."""
        if self._status is None:
            try:
                fd = os.open(self._path, os.O_RDONLY)
            except FileNotFoundError:
                reader = self.store._chunk_reader(self.metainfo.digest)
                if reader is None:
                    raise
                return reader
            return _FlatIO(fd)
        # O_RDWR while incomplete (piece writes land here); a committed
        # blob is read-only. Completion does NOT reopen: commit is a
        # rename, so the fd keeps addressing the same inode the cache
        # path now names.
        return _FlatIO(os.open(self._path, os.O_RDWR))

    def _with_fd(self, op):
        """Run ``op(io)`` (a pread/pwrite) with the handle ref-counted.

        Teardown races are real: cancelling an _io_task does NOT stop a
        worker thread already inside os.pwrite, and closing the fd under
        it risks EBADF -- or, via fd-number reuse, a multi-MiB write into
        whatever file grabbed the number. So close() only marks closed;
        the LAST in-flight op (or close() itself when none are) actually
        closes, and new ops after close are refused."""
        with self._fd_lock:
            if self._fd_closed:
                raise PieceError("torrent closed")
            if self._fd is None:
                self._fd = self._open_io()
            self._fd_refs += 1
            fd = self._fd
        try:
            return op(fd)
        finally:
            with self._fd_lock:
                self._fd_refs -= 1
                if self._fd_closed and self._fd_refs == 0 and self._fd is not None:
                    self._fd.close()
                    self._fd = None

    def release_fd(self) -> None:
        """Drop the cached IO handle if no IO is in flight; the next
        piece IO reopens it. The dispatcher calls this when a torrent's
        last peer leaves, so a long-lived origin seeding thousands of
        blobs holds fds only for torrents with LIVE conns -- without
        this, steady-state fd usage grows with every blob ever served
        until EMFILE (and conn churn already guarantees idle torrents
        shed their peers). Best-effort: in-flight IO keeps the handle
        until close()."""
        with self._fd_lock:
            if self._fd_refs == 0 and self._fd is not None and not self._fd_closed:
                self._fd.close()
                self._fd = None

    def close(self) -> None:
        """Flush any unpersisted bitfield and retire the fd. Sync --
        callable from dispatcher teardown. Only incomplete torrents flush
        (a complete torrent has no sidecar; re-writing one after eviction
        would orphan a ._md file beside a deleted blob).

        The flush runs OFF the event loop when one is running, matching
        the periodic flusher and the commit path: in durability=fsync
        mode a sidecar write pays fsync+dirsync, and a watermark sweep
        tearing down many torrents would otherwise stall every conn pump
        for the duration (VERDICT r5 weak #3). Without a loop (tests,
        sync teardown) it blocks right here. Best-effort either way: the
        persisted bitfield may understate progress, never overstate it."""
        if self._bits_flusher is not None:
            self._bits_flusher.cancel()
            self._bits_flusher = None
        if self._status is not None and self._bits_dirty:
            status = self._status
            self._bits_dirty = False

            def _flush() -> None:
                try:
                    self.store.set_metadata(self.metainfo.digest, status)
                except Exception:
                    # Progress-only sidecar: a lost flush re-downloads at
                    # most the unflushed tail on resume.
                    _log.warning(
                        "final bitfield flush failed",
                        extra={"digest": self.metainfo.digest.hex},
                        exc_info=True,
                    )

            try:
                loop = asyncio.get_running_loop()
                loop.run_in_executor(None, _flush)
            except RuntimeError:
                # No loop, or the loop's executor already shut down
                # (process teardown): flush inline -- blocking here
                # beats losing the progress entirely.
                _flush()
        with self._fd_lock:
            self._fd_closed = True
            if self._fd_refs == 0 and self._fd is not None:
                self._fd.close()
                self._fd = None

    def promote(self, path: str) -> None:
        """Repoint a spool-backed torrent at its committed path (commit
        renamed the spool into the cache, same inode). New opens hit the
        cache path; an fd already open on the old name is unaffected."""
        with self._fd_lock:
            self._path = path
            self.spool_backed = False

    def read_piece(self, i: int) -> bytes:
        if not self.has_piece(i):
            raise PieceError(f"piece {i} not present")
        off = i * self.metainfo.piece_length
        ln = self.metainfo.piece_length_of(i)
        data = self._with_fd(lambda io_: io_.pread(ln, off))
        if len(data) != ln:
            raise PieceError(f"short read on piece {i}")
        return data

    async def write_piece(
        self,
        i: int,
        data: bytes | memoryview,
        remote_write=None,
    ) -> bool:
        """Verify + persist piece ``i``. Returns True when this write
        completed the torrent. Raises :class:`PieceError` on corrupt data
        (callers blacklist the sender). File IO runs off-loop so a disk
        stall can't freeze the scheduler. ``data`` may be a pooled
        memoryview flowing straight from the wire to ``os.pwrite`` --
        the caller releases its lease only after this returns.

        ``remote_write`` (leech-shard plane): an async callable taking
        the piece index that persists the already-verified bytes in the
        WORKER that received them -- the payload stays in its shared-
        memory slot and never crosses back to this process. It replaces
        only the data-write step; verify, duplicate checks, the bit
        mark, and commit all stay here, so the crash-resume invariant
        (bit set only after the data is durably written) holds
        unchanged. A remote write that fails (worker died mid-flight)
        raises, the piece stays unmarked, and the dispatcher requeues
        it like any peer error."""
        if self._status is None:
            # With endgame duplication a second copy of the final piece
            # can arrive after completion: a benign duplicate, never a
            # peer fault.
            return False
        if len(data) != self.metainfo.piece_length_of(i):
            raise PieceError(
                f"piece {i}: wrong length {len(data)} != "
                f"{self.metainfo.piece_length_of(i)}"
            )
        t0 = _time.perf_counter()
        if not await self._verifier.verify(data, self.metainfo.piece_hash(i)):
            raise PieceError(f"piece {i}: digest mismatch")
        self.verify_wall += _time.perf_counter() - t0
        if self._status is None or self._status.has(i):
            return False  # duplicate arrival (endgame copies are benign)
        # The data write runs OUTSIDE the lock: pieces occupy disjoint
        # offsets, so concurrent pwrites never conflict, and serializing
        # 4 MiB disk writes behind one asyncio.Lock was the round-4
        # pair-throughput cap. A duplicate slipping past the pre-check
        # rewrites identical bytes -- benign. Completion cannot race this
        # write: it requires every bit set, and piece i's bit is only set
        # below, after this write returns.
        t0 = _time.perf_counter()
        if remote_write is not None:
            await remote_write(i)
        else:
            await asyncio.to_thread(self._write_at, i, data)
        self.write_wall += _time.perf_counter() - t0
        async with self._lock:
            # Re-check under the lock: a concurrent writer of the same
            # final piece may have completed the torrent (set _status to
            # None) while this task parked on verify or the write.
            if self._status is None or self._status.has(i):
                return False
            self._status.set(i)
            if self._status.complete():
                if self._bits_flusher is not None:
                    self._bits_flusher.cancel()
                    self._bits_flusher = None
                self._bits_dirty = False

                def _commit() -> None:
                    # Off-loop: in durability=fsync mode this fsyncs the
                    # WHOLE blob -- seconds for multi-GiB, which on the
                    # loop would stall every conn pump on the agent.
                    self.store.commit_partial_file(self.metainfo.digest)
                    self.store.delete_metadata(
                        self.metainfo.digest, PieceStatusMetadata
                    )

                await asyncio.to_thread(_commit)
                self._status = None
                self._path = self.store.cache_path(self.metainfo.digest)
                return True
            self._mark_bits_dirty()
            return False

    def _write_at(self, i: int, data: bytes) -> None:
        self._with_fd(
            lambda io_: io_.pwrite(data, i * self.metainfo.piece_length)
        )

    def _mark_bits_dirty(self) -> None:
        self._bits_dirty = True
        if self._bits_flusher is None or self._bits_flusher.done():
            self._bits_flusher = asyncio.create_task(self._flush_bits_later())

    async def _flush_bits_later(self) -> None:
        await asyncio.sleep(self.BITS_FLUSH_SECONDS)
        async with self._lock:
            if self._status is not None and self._bits_dirty:
                # Off-loop: a sidecar write is small, but in fsync mode
                # it pays fsync+dirsync every flush.
                await asyncio.to_thread(
                    self.store.set_metadata, self.metainfo.digest, self._status
                )
                self._bits_dirty = False

    async def read_piece_async(self, i: int) -> bytes:
        """Off-loop :meth:`read_piece` for pump-context reads."""
        return await asyncio.to_thread(self.read_piece, i)

    async def flush_bits(self) -> None:
        """Persist the piece bitfield NOW (off-loop), ahead of the
        debounced flusher. The delta prefill hands its progress to a
        fresh Torrent immediately after closing this one -- waiting out
        the 200 ms debounce window (or racing close()'s fire-and-forget
        executor flush) would let the successor re-download pieces this
        torrent already verified and wrote."""
        async with self._lock:
            if self._status is not None and self._bits_dirty:
                await asyncio.to_thread(
                    self.store.set_metadata, self.metainfo.digest, self._status
                )
                self._bits_dirty = False


class AgentTorrentArchive:
    """Download-side archive: creates resumable torrents from metainfo.

    Mirrors ``lib/torrent/storage/agentstorage`` (metainfo via tracker,
    cache-file allocation, bitfield persistence) -- the metainfo fetch
    lives in the caller (scheduler) to keep this layer IO-free.
    """

    def __init__(self, store: CAStore, verifier: BatchedVerifier):
        self.store = store
        self.verifier = verifier

    def create_torrent(self, metainfo: MetaInfo) -> Torrent:
        # On-loop IO audit (VERDICT r5 #6): this runs on the loop (the
        # scheduler's sync control setup) and writes the initial bitfield
        # sidecar -- once per NEW torrent, not per piece, so the fsync-
        # mode cost is one sync per download start. Acceptable; the
        # per-piece paths (verify, data write, bitfield flush, commit,
        # close) all run off-loop.
        d = metainfo.digest
        if self.store.in_cache(d):
            # in_cache == committed (partials live at .part), so this is
            # always safe to seed.
            return Torrent(self.store, metainfo, self.verifier, complete=True)
        self.store.allocate_partial_file(d, metainfo.length)
        if self.store.get_metadata(d, PieceStatusMetadata) is None:
            self.store.set_metadata(d, PieceStatusMetadata(metainfo.num_pieces))
        return Torrent(self.store, metainfo, self.verifier, complete=False)


class OriginTorrentArchive:
    """Seed-side archive: torrents over committed CAStore blobs."""

    def __init__(self, store: CAStore, verifier: BatchedVerifier):
        self.store = store
        self.verifier = verifier

    def create_torrent(self, metainfo: MetaInfo) -> Torrent:
        if not self.store.in_cache(metainfo.digest):
            raise KeyError(str(metainfo.digest))
        return Torrent(self.store, metainfo, self.verifier, complete=True)

"""Multi-core seed-serve plane: sharded worker processes + sendfile serves.

The round-5/7 residual decomposition (PERF.md) pinned the remaining
data-plane bound to ONE core: the raw wire moves 1.0-1.4 GB/s while the
full stack does ~30% of it, all of it on the single event loop. The
leech half of the plane (verify -> bitfield -> commit) is already
off-GIL via the HashPool; the seed half -- read a piece, frame it, push
it down a socket -- still burned the main loop per byte. This module
shards that half across worker PROCESSES and makes each serve nearly
free:

- A :class:`ShardPool` supervisor forks ``data_plane_workers`` child
  processes (``scheduler:`` YAML knob on agent+origin, SIGHUP-resizable),
  each running its own event loop and conn pump.
- The scheduler's acceptor classifies inbound conns after the handshake:
  **seed-only conns** (our torrent is complete -- we will only ever
  serve) are handed to a worker via ``socket.send_fds`` together with a
  compact torrent descriptor (info hash, piece length, blob path, any
  bytes the parent's StreamReader already buffered). Leech conns stay on
  the main loop untouched.
- Workers serve PIECE_REQUESTs straight from a long-lived per-torrent
  blob fd: the 9-byte prefix + msgpack header go out under ``TCP_CORK``,
  the payload rides ``loop.sock_sendfile`` -- page cache to socket,
  skipping bufpool and userspace entirely on the seed hot path. A stale
  fd or an evicted blob closes the conn gracefully between frames; the
  remote re-announces and re-pulls (requeues) from healthy peers.
- Control flows over one ``AF_UNIX``/``SOCK_SEQPACKET`` socketpair per
  worker: parent -> worker conn handoffs (+fd), evict / lameduck / stop;
  worker -> parent per-shard counters (aggregated onto the main metrics
  mux under ``shard="data_plane_shard{n}"`` labels) and conn-closed /
  misbehavior verdicts, which the scheduler feeds back into connstate
  and the blacklist exactly as for main-loop conns.
- Lameduck drain fans out: the acceptor already refuses new conns, the
  workers let in-flight serves finish, and the drain loop's quiesce
  signal (:attr:`Scheduler.num_active_conns`) counts worker conns, so
  SIGTERM semantics from the degradation plane are preserved.

Workers are forked (not spawned): they inherit the armed failpoint
registry and logging config, cost no re-import, and run nothing but
stdlib + msgpack -- no JAX, no aiohttp, no store machinery. A crashed
worker is detected by control-socket EOF: its conn slots are released,
``data_plane_worker_crashes_total`` counts it, the resource sentinel
flags it as a breach, and the supervisor respawns the shard.
"""

from __future__ import annotations

import asyncio
import errno
import logging
import multiprocessing
import os
import signal
import socket
import time
from typing import Callable, Optional

import msgpack

from kraken_tpu.p2p.wire import MAX_HEADER, MAX_PAYLOAD, MsgType
from kraken_tpu.utils import failpoints, trace

_log = logging.getLogger("kraken.p2p.shard")

# Worker-side recv chunk and control-message bound. SEQPACKET preserves
# message boundaries; the only large field is the handoff residual (the
# few frames a fast leecher pipelined behind its handshake).
_CTRL_RECV = 1 << 18
_RECV_CHUNK = 1 << 16

# Parent-side identity of a handed-off conn, for slot release + events.
ConnClosedFn = Callable[[dict, str, bool], None]


def _cork(sock: socket.socket, on: bool) -> None:
    """Batch header+payload into MSS-sized segments (Linux TCP_CORK);
    uncorking flushes. Elsewhere fall back to toggling NODELAY, which
    gives the same flush-on-uncork edge without the strict batching."""
    try:
        if hasattr(socket, "TCP_CORK"):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_CORK, 1 if on else 0)
        else:  # pragma: no cover - non-Linux
            sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 0 if on else 1
            )
    except OSError:
        pass  # best-effort: correctness never depends on corking


class _Misbehavior(Exception):
    """Protocol violation by the remote (oversize payload, garbage
    header, out-of-range index): the conn closes and the verdict flows
    back to the parent's blacklist."""


_HAVE_SENDFILE = hasattr(os, "sendfile")
# errnos meaning "sendfile cannot serve THIS file/socket pair" (exotic
# fs, emulated kernel): fall back to pread+send for the serve, never
# fail the conn over the transport mechanism.
_SENDFILE_UNSUPPORTED = {
    getattr(errno, name, -1)
    for name in ("EINVAL", "ENOSYS", "EOPNOTSUPP", "ENOTSUP", "ESPIPE")
}


# ---------------------------------------------------------------------------
# Worker side (child process)
# ---------------------------------------------------------------------------

class _WorkerTorrent:
    __slots__ = (
        "name", "path", "piece_length", "length", "num_pieces",
        "file", "evicted_evt", "conns",
    )

    def __init__(self, desc: dict):
        self.name = desc["name"]
        self.path = desc["path"]
        self.piece_length = desc["plen"]
        self.length = desc["len"]
        self.num_pieces = desc["np"]
        self.file = None  # long-lived blob fd, opened on first serve
        self.evicted_evt = asyncio.Event()
        self.conns: set["_WorkerConn"] = set()

    def piece_length_of(self, i: int) -> int:
        return min(self.piece_length, self.length - i * self.piece_length)

    def open(self):
        if self.file is None:
            # Buffered binary handle: sock_sendfile's native path only
            # uses fileno() (positional os.sendfile -- safe concurrently).
            self.file = open(self.path, "rb")
        return self.file

    def close(self) -> None:
        if self.file is not None:
            try:
                self.file.close()
            finally:
                self.file = None


class _WorkerConn:
    __slots__ = ("cid", "sock", "torrent", "buf", "task", "peer", "ih", "tp")

    def __init__(self, cid: int, sock: socket.socket, torrent: _WorkerTorrent,
                 desc: dict):
        self.cid = cid
        self.sock = sock
        self.torrent = torrent
        self.buf = bytearray(desc.get("residual") or b"")
        self.task: Optional[asyncio.Task] = None
        self.peer = desc["peer"]
        self.ih = desc["ih"]
        # Conn-level trace context from the leecher's handshake (rode
        # the handoff descriptor); per-request PIECE_REQUEST "tp"
        # headers override it for finer nesting.
        self.tp = desc.get("tp") or ""


class _WorkerState:
    """Everything one shard process owns. Runs inside ``asyncio.run``."""

    def __init__(self, ctrl: socket.socket, shard: int, cfg: dict):
        self.ctrl = ctrl
        self.shard = shard
        # Idle churn mirrors the dispatcher's conn churn: a seed conn
        # that carries nothing for 2x the churn window frees its slot
        # (the remote redials if it still wants bytes).
        self.idle_timeout = max(1.0, 2.0 * float(cfg.get("churn_idle", 4.0)))
        self.torrents: dict[str, _WorkerTorrent] = {}
        self.conns: dict[int, _WorkerConn] = {}
        self.bytes_up = 0
        self.serves = 0
        self.lameduck = False
        self._stop_evt = asyncio.Event()
        self._stats_dirty = True
        # Finished serve spans awaiting shipment to the parent (fed by
        # the tracer's on_record hook; drained with the stats tick).
        # Bounded: a backlogged parent must cost spans, not RSS.
        self._span_buf: list[dict] = []

    # -- control channel ---------------------------------------------------

    def _on_ctrl(self) -> None:
        while True:
            try:
                data, fds, _flags, _addr = socket.recv_fds(
                    self.ctrl, _CTRL_RECV, 4
                )
            except BlockingIOError:
                return
            except OSError:
                data, fds = b"", []
            if not data:
                # Parent closed its end (stop/crash): drain and exit.
                self._stop_evt.set()
                return
            try:
                msg = msgpack.unpackb(data)
                self._handle_ctrl(msg, fds)
            except Exception:
                for fd in fds:
                    os.close(fd)
                _log.exception("shard %d: bad control message", self.shard)

    def _handle_ctrl(self, msg: dict, fds: list[int]) -> None:
        t = msg.get("t")
        if t == "conn":
            if not fds:
                return
            if self._stop_evt.is_set() or self.lameduck:
                # Late handoff into a draining worker (the parent sent
                # the conn before it saw our drain state): refuse by
                # closing -- the remote soft-retries another peer. The
                # closed verdict MUST still flow back, or the parent's
                # conn slot leaks and the drain wait never quiesces.
                for fd in fds:
                    os.close(fd)
                self._send(
                    {"t": "closed", "cid": msg["cid"],
                     "reason": "worker_refused", "detail": "draining",
                     "mis": False}
                )
                return
            sock = socket.socket(fileno=fds[0])
            for fd in fds[1:]:
                os.close(fd)
            sock.setblocking(False)
            try:
                # A whole piece should fit in the send buffer: sendfile
                # then completes in one or two syscalls instead of
                # ping-ponging EAGAIN -> add_writer -> retry per few
                # hundred KB (each round trip is an epoll_ctl pair plus
                # a loop wakeup -- measured 2x serve CPU on small
                # default buffers).
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDBUF,
                    max(4 << 20, msg.get("plen", 0) * 2),
                )
            except OSError:
                pass
            torrent = self.torrents.get(msg["name"])
            if torrent is None or torrent.evicted_evt.is_set():
                torrent = _WorkerTorrent(msg)
                self.torrents[msg["name"]] = torrent
            conn = _WorkerConn(msg["cid"], sock, torrent, msg)
            torrent.conns.add(conn)
            self.conns[conn.cid] = conn
            conn.task = asyncio.create_task(self._conn_loop(conn))
            self._stats_dirty = True
        elif t == "evict":
            torrent = self.torrents.get(msg["name"])
            if torrent is not None:
                # Graceful: conn loops observe the event BETWEEN frames,
                # so an in-flight sendfile completes (the unlinked inode
                # stays readable through the open fd), then the conn
                # closes and the remote requeues elsewhere.
                torrent.evicted_evt.set()
                if not torrent.conns:
                    torrent.close()
                    self.torrents.pop(msg["name"], None)
        elif t == "lameduck":
            self.lameduck = True
        elif t == "stop":
            self._stop_evt.set()
        elif t == "cfg":
            self.idle_timeout = max(
                1.0, 2.0 * float(msg.get("churn_idle", 4.0))
            )

    # -- frame plumbing ----------------------------------------------------

    async def _readexactly(self, conn: _WorkerConn, n: int) -> bytes:
        loop = asyncio.get_running_loop()
        while len(conn.buf) < n:
            chunk = await loop.sock_recv(conn.sock, _RECV_CHUNK)
            if not chunk:
                raise ConnectionResetError("remote closed")
            conn.buf += chunk
        out = bytes(conn.buf[:n])
        del conn.buf[:n]
        return out

    async def _read_frame(self, conn: _WorkerConn) -> tuple[int, dict]:
        """One wire frame (p2p/wire.py layout). Payload bytes -- always
        unsolicited on a seed conn -- are drained and dropped to keep
        framing; oversize or malformed input is misbehavior."""
        prefix = await self._readexactly(conn, 9)
        mtype = prefix[0]
        header_len = int.from_bytes(prefix[1:5], "big")
        payload_len = int.from_bytes(prefix[5:9], "big")
        if header_len > MAX_HEADER or payload_len > MAX_PAYLOAD:
            raise _Misbehavior(
                f"oversized frame: header={header_len} payload={payload_len}"
            )
        if payload_len > max(conn.torrent.piece_length, 1 << 20):
            raise _Misbehavior(f"oversize payload: {payload_len}")
        raw_header = await self._readexactly(conn, header_len) if header_len else b""
        try:
            header = msgpack.unpackb(raw_header) if header_len else {}
            if not isinstance(header, dict):
                raise ValueError("header not a map")
        except Exception as e:
            raise _Misbehavior(f"malformed header: {e}") from e
        # Drain-and-drop any payload: a seeder never asked for one.
        remaining = payload_len
        while remaining:
            got = await self._readexactly(conn, min(remaining, _RECV_CHUNK))
            remaining -= len(got)
        return mtype, header

    async def _wait_writable(self, sock: socket.socket) -> None:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        fd = sock.fileno()

        def ready() -> None:
            loop.remove_writer(fd)
            if not fut.done():
                fut.set_result(None)

        loop.add_writer(fd, ready)
        try:
            await fut
        except asyncio.CancelledError:
            loop.remove_writer(fd)
            raise

    async def _sendfile(self, conn: _WorkerConn, f, offset: int,
                        count: int) -> None:
        """Nonblocking ``os.sendfile`` with an inline fast path: after
        the previous piece drains, the (piece-sized, see SO_SNDBUF at
        adoption) send buffer almost always has room, so the common
        case is ONE syscall and zero event-loop round trips --
        ``loop.sock_sendfile``'s per-chunk add_writer/remove_writer
        dance measured at 2x the serve CPU on this path."""
        loop = asyncio.get_running_loop()
        fd = conn.sock.fileno()
        sent = 0
        while sent < count:
            try:
                n = os.sendfile(fd, f.fileno(), offset + sent, count - sent)
            except BlockingIOError:
                await self._wait_writable(conn.sock)
                continue
            if n == 0:
                raise ConnectionResetError("sendfile: remote closed")
            sent += n
            if sent < count:
                # Partial: buffer full mid-piece; wait before retrying
                # rather than spinning EAGAIN.
                await self._wait_writable(conn.sock)
        await asyncio.sleep(0)  # serve fairness between conns of a shard

    async def _serve_piece(self, conn: _WorkerConn, idx: int,
                           tp: str = "") -> None:
        """The hot path: prefix+header corked, payload via sendfile from
        the long-lived blob fd -- piece bytes never enter this process's
        userspace (page cache -> socket in the kernel).

        ``tp`` is the requester's traceparent (frame-level, falling back
        to the handshake's): present only on SAMPLED traces, in which
        case the serve gets a span that ships home to the parent's
        flight recorder -- the cross-process half of "one trace per
        pull"."""
        parent = trace.parse_traceparent(tp or conn.tp)
        if parent is not None and parent.sampled:
            with trace.span(
                "p2p.shard.serve", parent, piece=idx,
                peer=conn.peer[:12],
            ):
                await self._serve_piece_inner(conn, idx)
        else:
            await self._serve_piece_inner(conn, idx)

    async def _serve_piece_inner(self, conn: _WorkerConn, idx: int) -> None:
        hit = failpoints.fire("p2p.shard.serve.disconnect")
        if hit:
            if hit.delay_s:
                await asyncio.sleep(hit.delay_s)
            raise ConnectionResetError("failpoint p2p.shard.serve.disconnect")
        t = conn.torrent
        ln = t.piece_length_of(idx)
        header = msgpack.packb({"index": idx})
        head = (
            bytes([int(MsgType.PIECE_PAYLOAD)])
            + len(header).to_bytes(4, "big")
            + ln.to_bytes(4, "big")
            + header
        )
        loop = asyncio.get_running_loop()
        f = t.open()  # FileNotFoundError here = evicted under us
        _cork(conn.sock, True)
        try:
            await loop.sock_sendall(conn.sock, head)
            if _HAVE_SENDFILE:
                try:
                    await self._sendfile(
                        conn, f, idx * t.piece_length, ln
                    )
                except OSError as e:
                    if e.errno not in _SENDFILE_UNSUPPORTED:
                        raise
                    # Kernel/fs without sendfile for this pair: the
                    # pread fallback is correct, one userspace copy.
                    await self._serve_pread(conn, f, idx, ln)
            else:  # pragma: no cover - non-Linux
                await self._serve_pread(conn, f, idx, ln)
        finally:
            _cork(conn.sock, False)
        self.bytes_up += ln
        self.serves += 1
        self._stats_dirty = True

    async def _serve_pread(self, conn: _WorkerConn, f, idx: int,
                           ln: int) -> None:
        loop = asyncio.get_running_loop()
        data = os.pread(f.fileno(), ln, idx * conn.torrent.piece_length)
        if len(data) != ln:
            raise OSError(f"short read on piece {idx}")
        await loop.sock_sendall(conn.sock, data)

    async def _handle_frame(self, conn: _WorkerConn, mtype: int,
                            header: dict) -> None:
        if mtype == MsgType.PIECE_REQUEST:
            idx = header.get("index")
            t = conn.torrent
            if not isinstance(idx, int) or not 0 <= idx < t.num_pieces:
                raise _Misbehavior(f"piece index out of range: {idx!r}")
            await self._serve_piece(conn, idx, str(header.get("tp") or ""))
        elif mtype == MsgType.ERROR:
            raise ConnectionResetError(header.get("detail", "peer error"))
        # ANNOUNCE_PIECE / COMPLETE / CANCEL_PIECE / BITFIELD /
        # PIECE_PAYLOAD (already drained): progress chatter from the
        # leecher -- nothing for a pure seeder to act on.

    async def _conn_loop(self, conn: _WorkerConn) -> None:
        reason, detail, mis = "remote_closed", "", False
        t = conn.torrent
        evict_wait = asyncio.ensure_future(t.evicted_evt.wait())
        stop_wait = asyncio.ensure_future(self._stop_evt.wait())
        recv: Optional[asyncio.Future] = None
        try:
            while True:
                if t.evicted_evt.is_set():
                    reason = "evicted"
                    break
                if self._stop_evt.is_set():
                    reason = "drain_stop"
                    break
                recv = asyncio.ensure_future(self._read_frame(conn))
                done, _pending = await asyncio.wait(
                    {recv, evict_wait, stop_wait},
                    timeout=self.idle_timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if recv not in done:
                    recv.cancel()
                    recv = None
                    if evict_wait in done:
                        reason = "evicted"
                    elif stop_wait in done:
                        reason = "drain_stop"
                    else:
                        reason = "idle_conn"
                    break
                mtype, header = recv.result()
                recv = None
                # In-flight serves run INLINE here: eviction and drain
                # take effect between frames, never mid-sendfile.
                await self._handle_frame(conn, mtype, header)
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            reason, detail = "connection_error", str(e)
        except _Misbehavior as e:
            reason, detail, mis = "misbehavior", str(e), True
        except asyncio.CancelledError:
            reason = "cancelled"
        except Exception as e:  # a bad conn must not kill the shard
            reason, detail = "serve_error", str(e)
        finally:
            if recv is not None:
                recv.cancel()
            evict_wait.cancel()
            stop_wait.cancel()
            try:
                conn.sock.close()
            except OSError:
                pass
            self.conns.pop(conn.cid, None)
            t.conns.discard(conn)
            if not t.conns:
                # Shed the blob fd with the last conn (release_fd parity:
                # a long-lived shard must not hold fds for idle torrents).
                t.close()
                # Identity-guarded: an evicted torrent may have been
                # replaced in the registry by a fresh handoff after a
                # re-pull; popping by name alone would evict the NEW
                # object's registration and orphan its conns from any
                # later evict fan-out.
                if (
                    t.evicted_evt.is_set()
                    and self.torrents.get(t.name) is t
                ):
                    self.torrents.pop(t.name, None)
            self._send(
                {"t": "closed", "cid": conn.cid, "reason": reason,
                 "detail": detail, "mis": mis}
            )
            self._stats_dirty = True

    # -- stats + lifecycle -------------------------------------------------

    def _send(self, msg: dict) -> None:
        try:
            self.ctrl.send(msgpack.packb(msg))
        except (BlockingIOError, OSError):
            pass  # parent backlogged or gone; stats are best-effort

    def _send_stats(self) -> None:
        times = os.times()
        self._send({
            "t": "stats",
            "conns": len(self.conns),
            "bytes_up": self.bytes_up,
            "serves": self.serves,
            "cpu_s": times.user + times.system,
            "lameduck": self.lameduck,
        })
        self._stats_dirty = False
        self._ship_spans()
        self._ship_profile()

    _SPAN_BUF_MAX = 2048  # drop-oldest bound on the shipping buffer
    _SPAN_BATCH = 64  # spans per SEQPACKET message (size-bounded frames)

    def _on_span(self, d: dict) -> None:
        self._span_buf.append(d)
        if len(self._span_buf) > self._SPAN_BUF_MAX:
            del self._span_buf[: -self._SPAN_BUF_MAX]

    def _ship_spans(self) -> None:
        """Drain finished serve spans home; the parent adopts them into
        its flight recorder (record_foreign) so /debug/trace and the
        dump triggers see worker serves like any main-loop span."""
        while self._span_buf:
            batch = self._span_buf[: self._SPAN_BATCH]
            del self._span_buf[: self._SPAN_BATCH]
            self._send({"t": "spans", "spans": batch})

    def _ship_profile(self) -> None:
        """Drain this shard's folded-stack delta home (utils/profiler.py
        restarted its sampler post-fork): the parent adopts it under our
        node stamp so /debug/pprof/profile -- and a `kraken-tpu flame`
        collapse -- covers the whole node, shards included. Batched at
        the span-shipping size: one SEQPACKET datagram of hundreds of
        deep stacks would exceed the control socket's send buffer (and
        the parent's recv bound), losing the already-drained samples."""
        from kraken_tpu.utils import profiler

        while True:
            batch = profiler.PROFILER.drain_pending(
                max_stacks=self._SPAN_BATCH
            )
            if batch is None:
                return
            self._send({"t": "prof", **batch})

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        self.ctrl.setblocking(False)
        loop.add_reader(self.ctrl.fileno(), self._on_ctrl)
        # This fork inherited the parent's tracer wholesale: keep the
        # (pre-fork) config, but drop the parent's recorded spans --
        # they already live in the parent's ring -- stamp the shard on
        # the node id, and buffer this process's spans for shipment.
        trace.TRACER.recorder.clear()
        trace.TRACER.node = (
            f"{trace.TRACER.node}/shard{self.shard}"
            if trace.TRACER.node else f"shard{self.shard}"
        )
        trace.TRACER.on_record = self._on_span
        # Same story for the sampling profiler: the fork inherited its
        # config but killed its thread (and may have inherited mid-held
        # locks) -- restart clean with the shard's node stamp and ship
        # mode on, so this process's stacks ride the stats tick home.
        from kraken_tpu.utils import profiler

        profiler.PROFILER.restart_in_child(trace.TRACER.node)
        self._send({"t": "ready", "pid": os.getpid()})
        try:
            while not self._stop_evt.is_set():
                try:
                    await asyncio.wait_for(self._stop_evt.wait(), 0.25)
                except asyncio.TimeoutError:
                    pass
                if self._stats_dirty or self.conns:
                    self._send_stats()
        finally:
            # Graceful drain: conn loops observed _stop_evt and are
            # finishing their in-flight serve; give them a beat, then cut.
            tasks = [c.task for c in list(self.conns.values()) if c.task]
            if tasks:
                await asyncio.wait(tasks, timeout=1.0)
            for c in list(self.conns.values()):
                if c.task:
                    c.task.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
            for t in list(self.torrents.values()):
                t.close()
            self._send_stats()
            loop.remove_reader(self.ctrl.fileno())
            try:
                self.ctrl.close()
            except OSError:
                pass


def _worker_main(ctrl: socket.socket, parent_fd: int, shard: int,
                 cfg: dict) -> None:
    """Child-process entry (fork start method). Resets inherited signal
    plumbing -- the parent's asyncio handlers reference a loop this
    process must never touch -- then runs the shard's own loop."""
    try:
        signal.set_wakeup_fd(-1)
    except (ValueError, OSError):  # pragma: no cover
        pass
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent ^C handles us
    try:
        signal.signal(signal.SIGHUP, signal.SIG_IGN)
    except (OSError, ValueError):  # pragma: no cover
        pass
    if parent_fd >= 0:
        # The fork duplicated the PARENT's end of the socketpair into
        # this process; holding it open would mask parent-death EOF.
        try:
            os.close(parent_fd)
        except OSError:
            pass
    try:
        asyncio.run(_WorkerState(ctrl, shard, cfg).run())
    except KeyboardInterrupt:  # pragma: no cover
        pass


# ---------------------------------------------------------------------------
# Parent side (supervisor)
# ---------------------------------------------------------------------------

class _Worker:
    __slots__ = (
        "shard", "proc", "sock", "conns", "retiring",
        "last_bytes", "last_serves", "cpu_s",
    )

    def __init__(self, shard: int, proc, sock: socket.socket):
        self.shard = shard
        self.proc = proc
        self.sock = sock
        self.conns = 0  # parent-side estimate (handoffs - closes)
        self.retiring = False
        self.last_bytes = 0
        self.last_serves = 0
        self.cpu_s = 0.0

    @property
    def label(self) -> str:
        return f"data_plane_shard{self.shard}"


class ShardPool:
    """Supervisor for the seed-serve worker processes. One per scheduler;
    all methods run on the scheduler's event loop."""

    def __init__(
        self,
        size: int,
        *,
        churn_idle_seconds: float = 4.0,
        on_conn_closed: ConnClosedFn | None = None,
        component: str = "p2p",
    ):
        self._target = max(0, size)
        self.churn_idle = churn_idle_seconds
        self._on_conn_closed = on_conn_closed or (lambda desc, r, m: None)
        self.component = component
        self._workers: dict[int, _Worker] = {}
        self._conns: dict[int, tuple[int, dict]] = {}  # cid -> (shard, desc)
        self._next_cid = 0
        self._stopping = False
        self.lameduck = False
        self._reap_tasks: set[asyncio.Task] = set()
        from kraken_tpu.utils.metrics import REGISTRY

        self._c_handoffs = REGISTRY.counter(
            "data_plane_handoffs_total",
            "Seed conns handed to worker shards, by shard",
        )
        self._c_fallbacks = REGISTRY.counter(
            "data_plane_handoff_fallbacks_total",
            "Seed conns kept on the main loop (no shard could take them)",
        )
        self._c_crashes = REGISTRY.counter(
            "data_plane_worker_crashes_total",
            "Worker shards that exited without being asked to",
        )
        self._g_workers = REGISTRY.gauge(
            "data_plane_workers", "Configured seed-serve worker processes"
        )
        self._g_alive = REGISTRY.gauge(
            "data_plane_workers_alive", "Live seed-serve worker processes"
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for shard in range(self._target):
            self._spawn(shard)
        self._g_workers.set(self._target, component=self.component)

    def _spawn(self, shard: int) -> None:
        parent_sock, child_sock = socket.socketpair(
            socket.AF_UNIX, socket.SOCK_SEQPACKET
        )
        ctx = multiprocessing.get_context("fork")
        proc = ctx.Process(
            target=_worker_main,
            args=(
                child_sock, parent_sock.fileno(), shard,
                {"churn_idle": self.churn_idle},
            ),
            daemon=True,  # backstop: never outlive the node process
            name=f"kraken-data-plane-shard{shard}",
        )
        proc.start()
        child_sock.close()
        parent_sock.setblocking(False)
        w = _Worker(shard, proc, parent_sock)
        self._workers[shard] = w
        asyncio.get_running_loop().add_reader(
            parent_sock.fileno(), self._on_worker_msg, shard
        )
        self._g_alive.set(self.alive_workers, component=self.component)
        _log.info(
            "data-plane shard spawned",
            extra={"shard": shard, "pid": proc.pid},
        )

    def resize(self, size: int) -> None:
        """SIGHUP live resize: grow spawns fresh shards; shrink retires
        the highest shards -- they finish in-flight serves, close their
        conns, and exit; their slots release through the normal closed
        verdicts."""
        size = max(0, size)
        self._target = size
        self._g_workers.set(size, component=self.component)
        live = sorted(
            s for s, w in self._workers.items() if not w.retiring
        )
        for shard in range(size):
            if shard not in self._workers:
                self._spawn(shard)
        for shard in live:
            if shard >= size:
                w = self._workers[shard]
                w.retiring = True
                self._send(w, {"t": "stop"})

    def enter_lameduck(self) -> None:
        self.lameduck = True
        for w in self._workers.values():
            self._send(w, {"t": "lameduck"})

    def evict(self, name_hex: str) -> None:
        """A blob left the store (eviction, quarantine, unseed): every
        shard drops its fd and closes that torrent's conns gracefully."""
        for w in self._workers.values():
            self._send(w, {"t": "evict", "name": name_hex})

    def reconfigure(self, churn_idle_seconds: float) -> None:
        self.churn_idle = churn_idle_seconds
        for w in self._workers.values():
            self._send(w, {"t": "cfg", "churn_idle": churn_idle_seconds})

    async def stop(self) -> None:
        """Graceful teardown: ask every worker to drain, join with a
        bound, hard-kill stragglers, release any conn slots still
        attributed to shards. Reaps every child -- zero orphans is the
        soak harness's audit line."""
        self._stopping = True
        workers = list(self._workers.values())
        self._workers.clear()
        loop = asyncio.get_running_loop()
        for w in workers:
            try:
                loop.remove_reader(w.sock.fileno())
            except (OSError, ValueError):
                pass
            self._send(w, {"t": "stop"})

        def _join_all() -> None:
            deadline = time.monotonic() + 3.0
            for w in workers:
                w.proc.join(max(0.1, deadline - time.monotonic()))
            for w in workers:
                if w.proc.is_alive():
                    w.proc.terminate()
                    w.proc.join(1.0)
                if w.proc.is_alive():  # pragma: no cover - last resort
                    w.proc.kill()
                    w.proc.join(1.0)

        await asyncio.to_thread(_join_all)
        for w in workers:
            try:
                w.sock.close()
            except OSError:
                pass
            try:
                w.proc.close()  # releases the mp sentinel fd
            except (OSError, ValueError):  # pragma: no cover
                pass  # close() raises ValueError while still alive
        for cid, (shard, desc) in list(self._conns.items()):
            self._conns.pop(cid, None)
            self._safe_conn_closed(desc, "pool_stop", False)
        self._g_alive.set(0, component=self.component)
        for t in list(self._reap_tasks):
            t.cancel()
        if self._reap_tasks:
            await asyncio.gather(*self._reap_tasks, return_exceptions=True)

    # -- handoff -----------------------------------------------------------

    @property
    def can_accept(self) -> bool:
        return (
            not self._stopping
            and not self.lameduck
            and any(not w.retiring for w in self._workers.values())
        )

    @property
    def num_conns(self) -> int:
        """Live handed-off conns -- counted into the scheduler's drain
        quiesce signal."""
        return len(self._conns)

    def try_handoff(self, fd: int, desc: dict) -> bool:
        """Ship a handshaken seed conn (by fd) to the least-loaded shard.
        False = no shard could take it right now (all retiring, control
        channel backlogged); the caller keeps the conn on the main loop."""
        if not self.can_accept:
            self._c_fallbacks.inc()
            return False
        cid = self._next_cid
        self._next_cid += 1
        payload = msgpack.packb({"t": "conn", "cid": cid, **desc})
        candidates = sorted(
            (w for w in self._workers.values() if not w.retiring),
            key=lambda w: w.conns,
        )
        for w in candidates:
            try:
                socket.send_fds(w.sock, [payload], [fd])
            except (BlockingIOError, OSError):
                continue
            w.conns += 1
            self._conns[cid] = (w.shard, desc)
            self._c_handoffs.inc(shard=w.label)
            return True
        self._c_fallbacks.inc()
        return False

    # -- worker messages ---------------------------------------------------

    def _send(self, w: _Worker, msg: dict) -> None:
        try:
            w.sock.send(msgpack.packb(msg))
        except (BlockingIOError, OSError):
            pass  # worker gone or backlogged; EOF handling catches death

    def _on_worker_msg(self, shard: int) -> None:
        w = self._workers.get(shard)
        if w is None:
            return
        while True:
            try:
                data = w.sock.recv(_CTRL_RECV)
            except BlockingIOError:
                return
            except OSError:
                data = b""
            if not data:
                self._worker_gone(shard)
                return
            try:
                self._handle_worker_msg(w, msgpack.unpackb(data))
            except Exception:
                _log.exception("bad message from shard %d", shard)

    def _handle_worker_msg(self, w: _Worker, msg: dict) -> None:
        t = msg.get("t")
        if t == "stats":
            from kraken_tpu.utils.metrics import record_data_plane_shard

            w.cpu_s = float(msg.get("cpu_s", 0.0))
            record_data_plane_shard(
                w.label,
                conns=msg.get("conns", 0),
                bytes_delta=max(0, msg.get("bytes_up", 0) - w.last_bytes),
                serves_delta=max(0, msg.get("serves", 0) - w.last_serves),
                cpu_seconds=w.cpu_s,
            )
            w.last_bytes = msg.get("bytes_up", w.last_bytes)
            w.last_serves = msg.get("serves", w.last_serves)
        elif t == "closed":
            entry = self._conns.pop(msg.get("cid"), None)
            w.conns = max(0, w.conns - 1)
            if entry is not None:
                _shard, desc = entry
                self._safe_conn_closed(
                    desc, msg.get("reason", ""), bool(msg.get("mis"))
                )
        elif t == "spans":
            # Worker serve spans come home: adopt them so the parent's
            # /debug/trace and flight-recorder dumps hold the WHOLE
            # data plane, forked halves included.
            trace.TRACER.record_foreign(msg.get("spans") or [])
        elif t == "prof":
            # Folded-stack deltas from the shard's own sampler: one
            # /debug/pprof/profile (and one flame collapse) covers the
            # main loop AND the forked serve plane.
            from kraken_tpu.utils import profiler

            profiler.PROFILER.record_foreign(
                str(msg.get("node") or w.label),
                msg.get("stacks") or [],
                msg.get("planes") or {},
            )
        elif t == "ready":
            pass

    def _safe_conn_closed(self, desc: dict, reason: str, mis: bool) -> None:
        try:
            self._on_conn_closed(desc, reason, mis)
        except Exception:
            _log.exception("shard conn-closed callback failed")

    def _worker_gone(self, shard: int) -> None:
        w = self._workers.pop(shard, None)
        if w is None:
            return
        loop = asyncio.get_running_loop()
        try:
            loop.remove_reader(w.sock.fileno())
        except (OSError, ValueError):
            pass
        try:
            w.sock.close()
        except OSError:
            pass
        # Every conn this shard held is gone with it: release the slots
        # so the remotes can redial (onto another shard or the main loop).
        for cid, (s, desc) in list(self._conns.items()):
            if s == shard:
                self._conns.pop(cid, None)
                self._safe_conn_closed(desc, "worker_exit", False)
        expected = w.retiring or self._stopping
        if not expected:
            self._c_crashes.inc(shard=w.label)
            _log.warning(
                "data-plane shard died unexpectedly; respawning",
                extra={"shard": shard, "pid": w.proc.pid},
            )

        def _reap_and_respawn() -> None:
            t = asyncio.create_task(self._reap(w, shard))
            self._reap_tasks.add(t)
            t.add_done_callback(self._reap_tasks.discard)

        _reap_and_respawn()
        self._g_alive.set(self.alive_workers, component=self.component)

    async def _reap(self, w: _Worker, shard: int) -> None:
        def _join() -> None:
            w.proc.join(2.0)
            if w.proc.is_alive():  # pragma: no cover
                w.proc.terminate()
                w.proc.join(1.0)

        await asyncio.to_thread(_join)
        try:
            w.proc.close()
        except (OSError, ValueError):  # pragma: no cover
            pass  # close() raises ValueError while still alive
        # Respawn on crash, but ALSO when a retiring shard exits while
        # the target has grown back over it (shrink-then-grow race: the
        # grow saw the old shard still in the table and spawned nothing,
        # so this exit is the only chance to restore the pool size).
        if (
            not self._stopping
            and shard < self._target
            and shard not in self._workers
        ):
            self._spawn(shard)

    # -- introspection (sentinel / tests) ----------------------------------

    @property
    def alive_workers(self) -> int:
        return sum(1 for w in self._workers.values() if w.proc.is_alive())

    @property
    def expected_workers(self) -> int:
        return self._target

    def worker_info(self) -> list[dict]:
        """Per-shard pid/liveness/conn snapshot for the resource sentinel
        (child fd+RSS aggregation, crash reap-check) and /debug surfaces."""
        return [
            {
                "shard": w.shard,
                "pid": w.proc.pid,
                "alive": w.proc.is_alive(),
                "retiring": w.retiring,
                "conns": w.conns,
                "cpu_s": w.cpu_s,
            }
            for w in sorted(self._workers.values(), key=lambda w: w.shard)
        ]

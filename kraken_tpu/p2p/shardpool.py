"""Multi-core seed-serve plane: sharded worker processes + sendfile serves.

The round-5/7 residual decomposition (PERF.md) pinned the remaining
data-plane bound to ONE core: the raw wire moves 1.0-1.4 GB/s while the
full stack does ~30% of it, all of it on the single event loop. The
leech half of the plane (verify -> bitfield -> commit) is already
off-GIL via the HashPool; the seed half -- read a piece, frame it, push
it down a socket -- still burned the main loop per byte. This module
shards that half across worker PROCESSES and makes each serve nearly
free:

- A :class:`ShardPool` supervisor forks ``data_plane_workers`` child
  processes (``scheduler:`` YAML knob on agent+origin, SIGHUP-resizable),
  each running its own event loop and conn pump.
- The scheduler's acceptor classifies inbound conns after the handshake:
  **seed-only conns** (our torrent is complete -- we will only ever
  serve) are handed to a worker via ``socket.send_fds`` together with a
  compact torrent descriptor (info hash, piece length, blob path, any
  bytes the parent's StreamReader already buffered). Leech conns stay on
  the main loop untouched.
- Workers serve PIECE_REQUESTs straight from a long-lived per-torrent
  blob fd: the 9-byte prefix + msgpack header go out under ``TCP_CORK``,
  the payload rides ``loop.sock_sendfile`` -- page cache to socket,
  skipping bufpool and userspace entirely on the seed hot path. A stale
  fd or an evicted blob closes the conn gracefully between frames; the
  remote re-announces and re-pulls (requeues) from healthy peers.
- Control flows over one ``AF_UNIX``/``SOCK_SEQPACKET`` socketpair per
  worker: parent -> worker conn handoffs (+fd), evict / lameduck / stop;
  worker -> parent per-shard counters (aggregated onto the main metrics
  mux under ``shard="data_plane_shard{n}"`` labels) and conn-closed /
  misbehavior verdicts, which the scheduler feeds back into connstate
  and the blacklist exactly as for main-loop conns.
- Lameduck drain fans out: the acceptor already refuses new conns, the
  workers let in-flight serves finish, and the drain loop's quiesce
  signal (:attr:`Scheduler.num_active_conns`) counts worker conns, so
  SIGTERM semantics from the degradation plane are preserved.

Workers are forked (not spawned): they inherit the armed failpoint
registry and logging config, cost no re-import, and run nothing but
stdlib + msgpack -- no JAX, no aiohttp, no store machinery. A crashed
worker is detected by control-socket EOF: its conn slots are released,
``data_plane_worker_crashes_total`` counts it, the resource sentinel
flags it as a breach, and the supervisor respawns the shard.

**Leech plane** (``leech_workers`` knob, shipped 0 = off): the same pool
machinery in download mode. Active-download conns -- dialed or accepted
while our torrent is still partial -- hand off post-handshake just like
seed conns, but the descriptor carries ``leech``/``have``/``wr`` and the
parent registers a :class:`~kraken_tpu.p2p.conn.LeechConnProxy` the
dispatcher drives like any Conn. Division of labor per piece:

- WORKER: recv pump + frame parse, landing PIECE_PAYLOAD bytes straight
  into a leased slot of a per-worker :class:`~kraken_tpu.utils.bufpool.
  SlabRing` -- an anonymous shared ``mmap`` created pre-fork, so only
  the slot INDEX crosses the control channel, never the payload.
- PARENT: bookkeeping only. The dispatcher's normal ``write_piece`` flow
  verifies the slot bytes zero-copy through the shared
  ``BatchedVerifier`` (TPU ``hash_batch`` when the agent's hasher is
  TPU-backed, so verify amortizes across concurrent arrivals), then --
  on a good digest -- sends a ``write`` verdict instead of pwriting.
- WORKER: ``os.pwrite`` from the slot via its long-lived writable
  per-torrent fd, frees the slot, acks ``written``; only then does the
  parent mark the bitfield, preserving the crash-resume invariant (a
  set bit implies bytes on disk). Corrupt pieces never get a ``write``
  verdict: the parent's lease release sends ``drop``, the slot frees
  without touching disk, and the misbehavior verdict escalates the
  blacklist exactly as on the main loop.

Outbound frames (piece requests, announce fanout, PEX) ride the control
channel as ``send`` messages; the worker also answers PIECE_REQUESTs
in-process from its parent-fed have-set, so a leech conn keeps seeding
what it already has without bouncing through the main loop. Inbound
acceptor fan-out stays handshake-in-parent + fd-pass (not SO_REUSEPORT:
the handshake needs parent-side torrent state -- see OPERATIONS.md).
"""

from __future__ import annotations

import asyncio
import errno
import logging
import multiprocessing
import os
import signal
import socket
import time
from typing import Callable, Optional

import msgpack

from kraken_tpu.p2p.wire import MAX_HEADER, MAX_PAYLOAD, Message, MsgType, frame_head
from kraken_tpu.utils import failpoints, trace
from kraken_tpu.utils.bufpool import SlabRing, _class_for as _bufpool_class_for

_log = logging.getLogger("kraken.p2p.shard")

# Worker-side recv chunk and control-message bound. SEQPACKET preserves
# message boundaries; the only large field is the handoff residual (the
# few frames a fast leecher pipelined behind its handshake).
_CTRL_RECV = 1 << 18
_RECV_CHUNK = 1 << 16

# Parent-side identity of a handed-off conn, for slot release + events.
ConnClosedFn = Callable[[dict, str, bool], None]


def _cork(sock: socket.socket, on: bool) -> None:
    """Batch header+payload into MSS-sized segments (Linux TCP_CORK);
    uncorking flushes. Elsewhere fall back to toggling NODELAY, which
    gives the same flush-on-uncork edge without the strict batching."""
    try:
        if hasattr(socket, "TCP_CORK"):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_CORK, 1 if on else 0)
        else:  # pragma: no cover - non-Linux
            sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 0 if on else 1
            )
    except OSError:
        pass  # best-effort: correctness never depends on corking


class _Misbehavior(Exception):
    """Protocol violation by the remote (oversize payload, garbage
    header, out-of-range index): the conn closes and the verdict flows
    back to the parent's blacklist."""


_HAVE_SENDFILE = hasattr(os, "sendfile")
# errnos meaning "sendfile cannot serve THIS file/socket pair" (exotic
# fs, emulated kernel): fall back to pread+send for the serve, never
# fail the conn over the transport mechanism.
_SENDFILE_UNSUPPORTED = {
    getattr(errno, name, -1)
    for name in ("EINVAL", "ENOSYS", "EOPNOTSUPP", "ENOTSUP", "ESPIPE")
}


# ---------------------------------------------------------------------------
# Worker side (child process)
# ---------------------------------------------------------------------------

class _WorkerTorrent:
    __slots__ = (
        "name", "path", "piece_length", "length", "num_pieces",
        "file", "evicted_evt", "conns", "writable", "have",
    )

    def __init__(self, desc: dict):
        self.name = desc["name"]
        self.path = desc["path"]
        self.piece_length = desc["plen"]
        self.length = desc["len"]
        self.num_pieces = desc["np"]
        self.file = None  # long-lived blob fd, opened on first serve
        self.evicted_evt = asyncio.Event()
        self.conns: set["_WorkerConn"] = set()
        # Leech plane: writable torrents open r+ (the ``.part`` the
        # parent preallocated) so verdict pwrites land here; ``have``
        # mirrors the PARENT's bitfield (seeded from the handoff
        # descriptor, grown by write acks and by the announce/complete
        # frames the parent fans out through us) and gates which
        # PIECE_REQUESTs this worker may answer in-process.
        self.writable = bool(desc.get("wr"))
        self.have: set[int] = set()
        bits = desc.get("have") or b""
        if bits:
            # Same LSB-first convention as dispatch._bits_to_set.
            self.have = {
                i for i in range(self.num_pieces)
                if i // 8 < len(bits) and bits[i // 8] >> (i % 8) & 1
            }

    def piece_length_of(self, i: int) -> int:
        return min(self.piece_length, self.length - i * self.piece_length)

    def open(self):
        if self.file is None:
            # Buffered binary handle: sock_sendfile's native path only
            # uses fileno() (positional os.sendfile -- safe concurrently),
            # as do the leech plane's os.pwrite calls (unbuffered, so the
            # parent's commit fsync sees every byte).
            self.file = open(self.path, "r+b" if self.writable else "rb")
        return self.file

    def close(self) -> None:
        if self.file is not None:
            try:
                self.file.close()
            finally:
                self.file = None


class _WorkerConn:
    __slots__ = (
        "cid", "sock", "torrent", "buf", "task", "peer", "ih", "tp",
        "leech", "wlock",
    )

    def __init__(self, cid: int, sock: socket.socket, torrent: _WorkerTorrent,
                 desc: dict):
        self.cid = cid
        self.sock = sock
        self.torrent = torrent
        self.buf = bytearray(desc.get("residual") or b"")
        self.task: Optional[asyncio.Task] = None
        self.peer = desc["peer"]
        self.ih = desc["ih"]
        # Conn-level trace context from the leecher's handshake (rode
        # the handoff descriptor); per-request PIECE_REQUEST "tp"
        # headers override it for finer nesting.
        self.tp = desc.get("tp") or ""
        # Leech conns interleave TWO writers on one socket: parent-
        # authored control frames (requests, announces) and in-process
        # piece serves. The lock keeps a corked serve atomic.
        self.leech = bool(desc.get("leech"))
        self.wlock = asyncio.Lock()


class _WorkerState:
    """Everything one shard process owns. Runs inside ``asyncio.run``."""

    def __init__(self, ctrl: socket.socket, shard: int, cfg: dict,
                 ring: SlabRing | None = None):
        self.ctrl = ctrl
        self.shard = shard
        # Idle churn mirrors the dispatcher's conn churn: a seed conn
        # that carries nothing for 2x the churn window frees its slot
        # (the remote redials if it still wants bytes).
        self.idle_timeout = max(1.0, 2.0 * float(cfg.get("churn_idle", 4.0)))
        self.torrents: dict[str, _WorkerTorrent] = {}
        self.conns: dict[int, _WorkerConn] = {}
        self.bytes_up = 0
        self.serves = 0
        # Leech plane: the shared slab (created PRE-fork so both sides
        # map the same pages; None on seed-only shards). This side's
        # free list is authoritative -- the parent only reads views.
        self.ring = ring
        self._ring_evt = asyncio.Event()  # a slot freed; leasers recheck
        self.bytes_down = 0
        self.pieces = 0
        self._write_tasks: set[asyncio.Task] = set()
        self.lameduck = False
        self._stop_evt = asyncio.Event()
        self._stats_dirty = True
        # Finished serve spans awaiting shipment to the parent (fed by
        # the tracer's on_record hook; drained with the stats tick).
        # Bounded: a backlogged parent must cost spans, not RSS.
        self._span_buf: list[dict] = []

    # -- control channel ---------------------------------------------------

    def _on_ctrl(self) -> None:
        while True:
            try:
                data, fds, _flags, _addr = socket.recv_fds(
                    self.ctrl, _CTRL_RECV, 4
                )
            except BlockingIOError:
                return
            except OSError:
                data, fds = b"", []
            if not data:
                # Parent closed its end (stop/crash): drain and exit.
                self._stop_evt.set()
                return
            try:
                msg = msgpack.unpackb(data)
                self._handle_ctrl(msg, fds)
            except Exception:
                for fd in fds:
                    os.close(fd)
                _log.exception("shard %d: bad control message", self.shard)

    def _handle_ctrl(self, msg: dict, fds: list[int]) -> None:
        t = msg.get("t")
        if t == "conn":
            if not fds:
                return
            if self._stop_evt.is_set() or self.lameduck:
                # Late handoff into a draining worker (the parent sent
                # the conn before it saw our drain state): refuse by
                # closing -- the remote soft-retries another peer. The
                # closed verdict MUST still flow back, or the parent's
                # conn slot leaks and the drain wait never quiesces.
                for fd in fds:
                    os.close(fd)
                self._send(
                    {"t": "closed", "cid": msg["cid"],
                     "reason": "worker_refused", "detail": "draining",
                     "mis": False}
                )
                return
            sock = socket.socket(fileno=fds[0])
            for fd in fds[1:]:
                os.close(fd)
            sock.setblocking(False)
            try:
                # A whole piece should fit in the send buffer: sendfile
                # then completes in one or two syscalls instead of
                # ping-ponging EAGAIN -> add_writer -> retry per few
                # hundred KB (each round trip is an epoll_ctl pair plus
                # a loop wakeup -- measured 2x serve CPU on small
                # default buffers).
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDBUF,
                    max(4 << 20, msg.get("plen", 0) * 2),
                )
                if msg.get("leech"):
                    # Download pump: the recv window should hold a
                    # couple of pipelined pieces so the remote keeps
                    # streaming while we drain into the ring.
                    sock.setsockopt(
                        socket.SOL_SOCKET, socket.SO_RCVBUF,
                        max(4 << 20, msg.get("plen", 0) * 2),
                    )
            except OSError:
                pass
            torrent = self.torrents.get(msg["name"])
            if torrent is None or torrent.evicted_evt.is_set():
                torrent = _WorkerTorrent(msg)
                self.torrents[msg["name"]] = torrent
            conn = _WorkerConn(msg["cid"], sock, torrent, msg)
            torrent.conns.add(conn)
            self.conns[conn.cid] = conn
            conn.task = asyncio.create_task(self._conn_loop(conn))
            self._stats_dirty = True
        elif t == "evict":
            torrent = self.torrents.get(msg["name"])
            if torrent is not None:
                # Graceful: conn loops observe the event BETWEEN frames,
                # so an in-flight sendfile completes (the unlinked inode
                # stays readable through the open fd), then the conn
                # closes and the remote requeues elsewhere.
                torrent.evicted_evt.set()
                if not torrent.conns:
                    torrent.close()
                    self.torrents.pop(msg["name"], None)
        elif t == "lameduck":
            self.lameduck = True
        elif t == "stop":
            self._stop_evt.set()
        elif t == "cfg":
            self.idle_timeout = max(
                1.0, 2.0 * float(msg.get("churn_idle", 4.0))
            )
        elif t == "send":
            # Parent-authored outbound frames for one leech conn
            # (requests, announce fanout, PEX). Announce/complete also
            # grow the torrent's have-set, so in-process serves track
            # pieces the parent landed through OTHER conns.
            conn = self.conns.get(msg.get("cid"))
            if conn is not None:
                task = asyncio.create_task(
                    self._send_frames(conn, msg.get("frames") or [])
                )
                self._write_tasks.add(task)
                task.add_done_callback(self._write_tasks.discard)
        elif t == "write":
            # Verify verdict: good digest. pwrite from the slot, free
            # it, ack -- the parent marks the bitfield only on our ack.
            task = asyncio.create_task(self._do_write(msg))
            self._write_tasks.add(task)
            task.add_done_callback(self._write_tasks.discard)
        elif t == "drop":
            # Slot abandoned parent-side (corrupt piece, duplicate,
            # conn torn down mid-verify): free without touching disk.
            self._free_slot(msg.get("slot"))
        elif t == "close":
            # Parent-initiated close (proxy.close echoed down). The
            # conn loop's finally still sends the closed verdict; the
            # proxy is already closed, so it no-ops on arrival.
            conn = self.conns.get(msg.get("cid"))
            if conn is not None and conn.task is not None:
                conn.task.cancel()

    # -- leech plane (slot recv + verdict writes + parent frames) ----------

    def _free_slot(self, slot) -> None:
        if self.ring is None or not isinstance(slot, int):
            return
        self.ring.release(slot)
        self._ring_evt.set()  # wake any pump parked on a full ring

    async def _lease_slot(self) -> int:
        """Claim a ring slot, waiting while the ring is full. The wait IS
        the backpressure: the pump stops reading, the kernel stops
        acking, TCP throttles the remote -- no bytes are dropped."""
        while True:
            slot = self.ring.lease()
            if slot is not None:
                return slot
            self._ring_evt.clear()
            await self._ring_evt.wait()

    async def _recv_into_slot(self, conn: _WorkerConn, slot: int,
                              n: int) -> None:
        """Land ``n`` payload bytes directly in the slot: residual bytes
        already buffered first, then ``sock_recv_into`` the rest -- the
        payload is written exactly once, by the kernel."""
        view = self.ring.view(slot, n)
        got = 0
        if conn.buf:
            take = min(len(conn.buf), n)
            view[:take] = conn.buf[:take]
            del conn.buf[:take]
            got = take
        loop = asyncio.get_running_loop()
        while got < n:
            r = await loop.sock_recv_into(conn.sock, view[got:])
            if not r:
                raise ConnectionResetError("remote closed mid-piece")
            got += r

    async def _send_frames(self, conn: _WorkerConn, frames: list) -> None:
        """Write parent-authored frames to the conn's socket (wire.py
        layout via the shared ``frame_head``)."""
        out = bytearray()
        for mt, header, payload in frames:
            header = header or {}
            payload = payload or b""
            if mt == int(MsgType.ANNOUNCE_PIECE):
                idx = header.get("index")
                if isinstance(idx, int):
                    conn.torrent.have.add(idx)
            elif mt == int(MsgType.COMPLETE):
                conn.torrent.have.update(range(conn.torrent.num_pieces))
            packed = msgpack.packb(header)
            out += frame_head(mt, packed, len(payload))
            out += payload
        if not out:
            return
        loop = asyncio.get_running_loop()
        try:
            async with conn.wlock:
                await loop.sock_sendall(conn.sock, bytes(out))
        except (ConnectionError, OSError):
            pass  # the conn loop's recv observes the death and reports it

    async def _do_write(self, msg: dict) -> None:
        slot, idx, name = msg.get("slot"), msg.get("idx"), msg.get("name")
        ok = False
        try:
            t = self.torrents.get(name)
            if t is None or not t.writable or not isinstance(idx, int):
                raise OSError(f"no writable torrent for {name!r}")
            ln = t.piece_length_of(idx)
            view = self.ring.view(slot, ln)
            f = t.open()
            # Off-loop: a disk stall must not freeze this shard's pumps.
            # os.pwrite on the raw fd bypasses the handle's buffering,
            # so the parent's commit path sees the bytes immediately.
            await asyncio.to_thread(
                os.pwrite, f.fileno(), view, idx * t.piece_length
            )
            t.have.add(idx)
            ok = True
        except Exception as e:
            _log.warning(
                "leech shard write failed",
                extra={"shard": self.shard, "piece": idx, "err": str(e)},
            )
        finally:
            # Free BEFORE acking: the bytes are on disk (or abandoned),
            # either way the slot's job is done.
            self._free_slot(slot)
            self._send({"t": "written", "slot": slot, "ok": ok})

    # -- frame plumbing ----------------------------------------------------

    async def _readexactly(self, conn: _WorkerConn, n: int) -> bytes:
        loop = asyncio.get_running_loop()
        while len(conn.buf) < n:
            chunk = await loop.sock_recv(conn.sock, _RECV_CHUNK)
            if not chunk:
                raise ConnectionResetError("remote closed")
            conn.buf += chunk
        out = bytes(conn.buf[:n])
        del conn.buf[:n]
        return out

    # Control-frame payloads worth forwarding to the parent whole (a
    # mid-stream BITFIELD's bits ride the payload): anything larger is
    # drained and dropped like the seed path.
    _FWD_PAYLOAD_MAX = 1 << 16

    async def _read_frame(
        self, conn: _WorkerConn
    ) -> tuple[int, dict, Optional[int], int, bytes]:
        """One wire frame (p2p/wire.py layout) as ``(mtype, header,
        slot, payload_len, payload)``.

        Seed conns: payload bytes are always unsolicited -- drained and
        dropped to keep framing (``slot=None, payload=b""``). Leech
        conns: a PIECE_PAYLOAD lands in a leased ring slot (``slot``
        set, the caller notifies the parent), small control payloads are
        captured for forwarding, and oversize or malformed input is
        misbehavior either way."""
        prefix = await self._readexactly(conn, 9)
        mtype = prefix[0]
        header_len = int.from_bytes(prefix[1:5], "big")
        payload_len = int.from_bytes(prefix[5:9], "big")
        if header_len > MAX_HEADER or payload_len > MAX_PAYLOAD:
            raise _Misbehavior(
                f"oversized frame: header={header_len} payload={payload_len}"
            )
        if payload_len > max(conn.torrent.piece_length, 1 << 20):
            raise _Misbehavior(f"oversize payload: {payload_len}")
        raw_header = await self._readexactly(conn, header_len) if header_len else b""
        try:
            header = msgpack.unpackb(raw_header) if header_len else {}
            if not isinstance(header, dict):
                raise ValueError("header not a map")
        except Exception as e:
            raise _Misbehavior(f"malformed header: {e}") from e
        if (
            conn.leech
            and self.ring is not None
            and payload_len
            and mtype == int(MsgType.PIECE_PAYLOAD)
        ):
            t = conn.torrent
            idx = header.get("index")
            if not isinstance(idx, int) or not 0 <= idx < t.num_pieces:
                raise _Misbehavior(f"piece index out of range: {idx!r}")
            if payload_len != t.piece_length_of(idx):
                raise _Misbehavior(
                    f"piece {idx}: wrong length {payload_len}"
                )
            slot = await self._lease_slot()
            try:
                await self._recv_into_slot(conn, slot, payload_len)
                # Failpoint p2p.shard.leech.corrupt: flip the first
                # payload byte IN the shared slot -- parent verify must
                # catch it, the ban must cross the fork boundary, the
                # pull must finish from healthy peers.
                if failpoints.fire("p2p.shard.leech.corrupt"):
                    self.ring.view(slot, 1)[0] ^= 0xFF
                # Failpoint p2p.shard.leech.disconnect: the remote dies
                # mid-transfer in a WORKER pump -- the piece requeues to
                # a healthy peer and the slot must come back.
                if failpoints.fire("p2p.shard.leech.disconnect"):
                    raise ConnectionResetError(
                        "failpoint p2p.shard.leech.disconnect"
                    )
            except BaseException:
                self._free_slot(slot)
                raise
            return mtype, header, slot, payload_len, b""
        if (
            conn.leech
            and payload_len
            and payload_len <= self._FWD_PAYLOAD_MAX
            and mtype != int(MsgType.PIECE_PAYLOAD)
        ):
            payload = await self._readexactly(conn, payload_len)
            return mtype, header, None, payload_len, payload
        # Drain-and-drop any payload: a seeder never asked for one.
        remaining = payload_len
        while remaining:
            got = await self._readexactly(conn, min(remaining, _RECV_CHUNK))
            remaining -= len(got)
        return mtype, header, None, payload_len, b""

    async def _wait_writable(self, sock: socket.socket) -> None:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        fd = sock.fileno()

        def ready() -> None:
            loop.remove_writer(fd)
            if not fut.done():
                fut.set_result(None)

        loop.add_writer(fd, ready)
        try:
            await fut
        except asyncio.CancelledError:
            loop.remove_writer(fd)
            raise

    async def _sendfile(self, conn: _WorkerConn, f, offset: int,
                        count: int) -> None:
        """Nonblocking ``os.sendfile`` with an inline fast path: after
        the previous piece drains, the (piece-sized, see SO_SNDBUF at
        adoption) send buffer almost always has room, so the common
        case is ONE syscall and zero event-loop round trips --
        ``loop.sock_sendfile``'s per-chunk add_writer/remove_writer
        dance measured at 2x the serve CPU on this path."""
        loop = asyncio.get_running_loop()
        fd = conn.sock.fileno()
        sent = 0
        while sent < count:
            try:
                n = os.sendfile(fd, f.fileno(), offset + sent, count - sent)
            except BlockingIOError:
                await self._wait_writable(conn.sock)
                continue
            if n == 0:
                raise ConnectionResetError("sendfile: remote closed")
            sent += n
            if sent < count:
                # Partial: buffer full mid-piece; wait before retrying
                # rather than spinning EAGAIN.
                await self._wait_writable(conn.sock)
        await asyncio.sleep(0)  # serve fairness between conns of a shard

    async def _serve_piece(self, conn: _WorkerConn, idx: int,
                           tp: str = "") -> None:
        """The hot path: prefix+header corked, payload via sendfile from
        the long-lived blob fd -- piece bytes never enter this process's
        userspace (page cache -> socket in the kernel).

        ``tp`` is the requester's traceparent (frame-level, falling back
        to the handshake's): present only on SAMPLED traces, in which
        case the serve gets a span that ships home to the parent's
        flight recorder -- the cross-process half of "one trace per
        pull"."""
        parent = trace.parse_traceparent(tp or conn.tp)
        if parent is not None and parent.sampled:
            with trace.span(
                "p2p.shard.serve", parent, piece=idx,
                peer=conn.peer[:12],
            ):
                await self._serve_piece_inner(conn, idx)
        else:
            await self._serve_piece_inner(conn, idx)

    async def _serve_piece_inner(self, conn: _WorkerConn, idx: int) -> None:
        hit = failpoints.fire("p2p.shard.serve.disconnect")
        if hit:
            if hit.delay_s:
                await asyncio.sleep(hit.delay_s)
            raise ConnectionResetError("failpoint p2p.shard.serve.disconnect")
        t = conn.torrent
        ln = t.piece_length_of(idx)
        head = frame_head(
            int(MsgType.PIECE_PAYLOAD), msgpack.packb({"index": idx}), ln
        )
        loop = asyncio.get_running_loop()
        f = t.open()  # FileNotFoundError here = evicted under us
        # wlock: on leech conns a parent-authored frame batch must not
        # interleave with the corked head+sendfile (seed conns never
        # contend -- the conn loop is the only writer).
        async with conn.wlock:
            _cork(conn.sock, True)
            try:
                await loop.sock_sendall(conn.sock, head)
                if _HAVE_SENDFILE:
                    try:
                        await self._sendfile(
                            conn, f, idx * t.piece_length, ln
                        )
                    except OSError as e:
                        if e.errno not in _SENDFILE_UNSUPPORTED:
                            raise
                        # Kernel/fs without sendfile for this pair: the
                        # pread fallback is correct, one userspace copy.
                        await self._serve_pread(conn, f, idx, ln)
                else:  # pragma: no cover - non-Linux
                    await self._serve_pread(conn, f, idx, ln)
            finally:
                _cork(conn.sock, False)
        self.bytes_up += ln
        self.serves += 1
        self._stats_dirty = True

    async def _serve_pread(self, conn: _WorkerConn, f, idx: int,
                           ln: int) -> None:
        loop = asyncio.get_running_loop()
        data = os.pread(f.fileno(), ln, idx * conn.torrent.piece_length)
        if len(data) != ln:
            raise OSError(f"short read on piece {idx}")
        await loop.sock_sendall(conn.sock, data)

    # Frame types a leech conn forwards to the parent's dispatcher (the
    # bookkeeping half: availability updates and peer gossip).
    _FORWARD_TYPES = frozenset(
        int(m) for m in (
            MsgType.ANNOUNCE_PIECE, MsgType.BITFIELD,
            MsgType.COMPLETE, MsgType.PEER_EXCHANGE,
        )
    )

    async def _handle_frame(self, conn: _WorkerConn, mtype: int,
                            header: dict, payload: bytes = b"") -> None:
        if mtype == MsgType.PIECE_REQUEST:
            idx = header.get("index")
            t = conn.torrent
            if not isinstance(idx, int) or not 0 <= idx < t.num_pieces:
                raise _Misbehavior(f"piece index out of range: {idx!r}")
            if conn.leech and idx not in t.have:
                # Same as the main-loop dispatcher: a request for a
                # piece we don't (yet) have is silently dropped -- the
                # remote re-requests after our next announce.
                return
            await self._serve_piece(conn, idx, str(header.get("tp") or ""))
        elif conn.leech and mtype in self._FORWARD_TYPES:
            # Dispatcher bookkeeping (peer availability, PEX gossip)
            # lives in the parent: ship the frame home. Payloads here
            # are small (bitfield bits) and size-capped at read time.
            self._send({
                "t": "frame", "cid": conn.cid, "mt": mtype, "h": header,
                **({"p": payload} if payload else {}),
            })
        elif mtype == MsgType.ERROR:
            raise ConnectionResetError(header.get("detail", "peer error"))
        # Remaining chatter (CANCEL_PIECE; ANNOUNCE/COMPLETE on a seed
        # conn; PIECE_PAYLOAD already drained): nothing to act on.

    async def _conn_loop(self, conn: _WorkerConn) -> None:
        reason, detail, mis = "remote_closed", "", False
        t = conn.torrent
        evict_wait = asyncio.ensure_future(t.evicted_evt.wait())
        stop_wait = asyncio.ensure_future(self._stop_evt.wait())
        recv: Optional[asyncio.Future] = None
        try:
            while True:
                if t.evicted_evt.is_set():
                    reason = "evicted"
                    break
                if self._stop_evt.is_set():
                    reason = "drain_stop"
                    break
                recv = asyncio.ensure_future(self._read_frame(conn))
                done, _pending = await asyncio.wait(
                    {recv, evict_wait, stop_wait},
                    timeout=self.idle_timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if recv not in done:
                    recv.cancel()
                    recv = None
                    if evict_wait in done:
                        reason = "evicted"
                    elif stop_wait in done:
                        reason = "drain_stop"
                    else:
                        reason = "idle_conn"
                    break
                mtype, header, slot, ln, payload = recv.result()
                recv = None
                if slot is not None:
                    # A complete piece landed in the shared ring: hand
                    # the parent its slot index for verify. Ownership
                    # transfers -- the slot comes back as a write
                    # verdict or a drop.
                    self.bytes_down += ln
                    self.pieces += 1
                    self._stats_dirty = True
                    delivered = self._send({
                        "t": "piece", "cid": conn.cid,
                        "idx": header.get("index"), "slot": slot, "ln": ln,
                    })
                    if not delivered:
                        # Parent backlogged/gone: the piece is lost (the
                        # request times out and requeues) but the slot
                        # MUST come back or the ring bleeds dry.
                        self._free_slot(slot)
                    continue
                # In-flight serves run INLINE here: eviction and drain
                # take effect between frames, never mid-sendfile.
                await self._handle_frame(conn, mtype, header, payload)
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            reason, detail = "connection_error", str(e)
        except _Misbehavior as e:
            reason, detail, mis = "misbehavior", str(e), True
        except asyncio.CancelledError:
            reason = "cancelled"
        except Exception as e:  # a bad conn must not kill the shard
            reason, detail = "serve_error", str(e)
        finally:
            if recv is not None:
                recv.cancel()
            evict_wait.cancel()
            stop_wait.cancel()
            try:
                conn.sock.close()
            except OSError:
                pass
            self.conns.pop(conn.cid, None)
            t.conns.discard(conn)
            if not t.conns:
                # Shed the blob fd with the last conn (release_fd parity:
                # a long-lived shard must not hold fds for idle torrents).
                t.close()
                # Identity-guarded: an evicted torrent may have been
                # replaced in the registry by a fresh handoff after a
                # re-pull; popping by name alone would evict the NEW
                # object's registration and orphan its conns from any
                # later evict fan-out.
                if (
                    t.evicted_evt.is_set()
                    and self.torrents.get(t.name) is t
                ):
                    self.torrents.pop(t.name, None)
            self._send(
                {"t": "closed", "cid": conn.cid, "reason": reason,
                 "detail": detail, "mis": mis}
            )
            self._stats_dirty = True

    # -- stats + lifecycle -------------------------------------------------

    def _send(self, msg: dict) -> bool:
        try:
            self.ctrl.send(msgpack.packb(msg))
            return True
        except (BlockingIOError, OSError):
            return False  # parent backlogged or gone; mostly best-effort

    def _send_stats(self) -> None:
        times = os.times()
        self._send({
            "t": "stats",
            "conns": len(self.conns),
            "bytes_up": self.bytes_up,
            "serves": self.serves,
            "bytes_down": self.bytes_down,
            "pieces": self.pieces,
            "cpu_s": times.user + times.system,
            "lameduck": self.lameduck,
        })
        self._stats_dirty = False
        self._ship_spans()
        self._ship_profile()

    _SPAN_BUF_MAX = 2048  # drop-oldest bound on the shipping buffer
    _SPAN_BATCH = 64  # spans per SEQPACKET message (size-bounded frames)

    def _on_span(self, d: dict) -> None:
        self._span_buf.append(d)
        if len(self._span_buf) > self._SPAN_BUF_MAX:
            del self._span_buf[: -self._SPAN_BUF_MAX]

    def _ship_spans(self) -> None:
        """Drain finished serve spans home; the parent adopts them into
        its flight recorder (record_foreign) so /debug/trace and the
        dump triggers see worker serves like any main-loop span."""
        while self._span_buf:
            batch = self._span_buf[: self._SPAN_BATCH]
            del self._span_buf[: self._SPAN_BATCH]
            self._send({"t": "spans", "spans": batch})

    def _ship_profile(self) -> None:
        """Drain this shard's folded-stack delta home (utils/profiler.py
        restarted its sampler post-fork): the parent adopts it under our
        node stamp so /debug/pprof/profile -- and a `kraken-tpu flame`
        collapse -- covers the whole node, shards included. Batched at
        the span-shipping size: one SEQPACKET datagram of hundreds of
        deep stacks would exceed the control socket's send buffer (and
        the parent's recv bound), losing the already-drained samples."""
        from kraken_tpu.utils import profiler

        while True:
            batch = profiler.PROFILER.drain_pending(
                max_stacks=self._SPAN_BATCH
            )
            if batch is None:
                return
            self._send({"t": "prof", **batch})

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        self.ctrl.setblocking(False)
        loop.add_reader(self.ctrl.fileno(), self._on_ctrl)
        # This fork inherited the parent's tracer wholesale: keep the
        # (pre-fork) config, but drop the parent's recorded spans --
        # they already live in the parent's ring -- stamp the shard on
        # the node id, and buffer this process's spans for shipment.
        trace.TRACER.recorder.clear()
        stamp = (
            f"leech{self.shard}" if self.ring is not None
            else f"shard{self.shard}"
        )
        trace.TRACER.node = (
            f"{trace.TRACER.node}/{stamp}" if trace.TRACER.node else stamp
        )
        trace.TRACER.on_record = self._on_span
        # Same story for the sampling profiler: the fork inherited its
        # config but killed its thread (and may have inherited mid-held
        # locks) -- restart clean with the shard's node stamp and ship
        # mode on, so this process's stacks ride the stats tick home.
        from kraken_tpu.utils import profiler

        profiler.PROFILER.restart_in_child(trace.TRACER.node)
        self._send({"t": "ready", "pid": os.getpid()})
        try:
            while not self._stop_evt.is_set():
                try:
                    await asyncio.wait_for(self._stop_evt.wait(), 0.25)
                except asyncio.TimeoutError:
                    pass
                if self._stats_dirty or self.conns:
                    self._send_stats()
        finally:
            # Graceful drain: conn loops observed _stop_evt and are
            # finishing their in-flight serve; give them a beat, then cut.
            tasks = [c.task for c in list(self.conns.values()) if c.task]
            if tasks:
                await asyncio.wait(tasks, timeout=1.0)
            for c in list(self.conns.values()):
                if c.task:
                    c.task.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
            # Verdict pwrites still in flight finish before the fds
            # close: the parent is awaiting their written acks.
            if self._write_tasks:
                await asyncio.gather(
                    *list(self._write_tasks), return_exceptions=True
                )
            for t in list(self.torrents.values()):
                t.close()
            self._send_stats()
            loop.remove_reader(self.ctrl.fileno())
            try:
                self.ctrl.close()
            except OSError:
                pass


def _worker_main(ctrl: socket.socket, parent_fd: int, shard: int,
                 cfg: dict, ring: SlabRing | None = None) -> None:
    """Child-process entry (fork start method). Resets inherited signal
    plumbing -- the parent's asyncio handlers reference a loop this
    process must never touch -- then runs the shard's own loop."""
    try:
        signal.set_wakeup_fd(-1)
    except (ValueError, OSError):  # pragma: no cover
        pass
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent ^C handles us
    try:
        signal.signal(signal.SIGHUP, signal.SIG_IGN)
    except (OSError, ValueError):  # pragma: no cover
        pass
    if parent_fd >= 0:
        # The fork duplicated the PARENT's end of the socketpair into
        # this process; holding it open would mask parent-death EOF.
        try:
            os.close(parent_fd)
        except OSError:
            pass
    try:
        asyncio.run(_WorkerState(ctrl, shard, cfg, ring).run())
    except KeyboardInterrupt:  # pragma: no cover
        pass


# ---------------------------------------------------------------------------
# Parent side (supervisor)
# ---------------------------------------------------------------------------

class _Worker:
    __slots__ = (
        "shard", "proc", "sock", "conns", "retiring",
        "last_bytes", "last_serves", "last_down", "last_pieces",
        "cpu_s", "prefix", "ring",
    )

    def __init__(self, shard: int, proc, sock: socket.socket,
                 prefix: str = "data_plane_shard",
                 ring: SlabRing | None = None):
        self.shard = shard
        self.proc = proc
        self.sock = sock
        self.conns = 0  # parent-side estimate (handoffs - closes)
        self.retiring = False
        self.last_bytes = 0
        self.last_serves = 0
        self.last_down = 0
        self.last_pieces = 0
        self.cpu_s = 0.0
        self.prefix = prefix
        # Leech shards only: the shared slab this worker's pumps fill.
        # A respawn gets a FRESH ring; the old mapping lives exactly as
        # long as in-flight parent-side views into it.
        self.ring = ring

    @property
    def label(self) -> str:
        return f"{self.prefix}{self.shard}"


class _SlotLease:
    """Parent-side lease on one shared-ring slot, attached to the
    PIECE_PAYLOAD :class:`~kraken_tpu.p2p.wire.Message` a leech worker
    announced. Mirrors the bufpool Lease contract the dispatcher already
    trusts: ``release()`` is idempotent and is THE single return point
    (the dispatcher's payload-task done-callback always calls it) --
    here it ships a ``drop`` so the worker frees the slot untouched.

    ``remote_write`` is the leech plane's replacement for the parent's
    pwrite (``Torrent.write_piece(..., remote_write=...)``): it consumes
    the lease, sends the good-digest ``write`` verdict, and resolves on
    the worker's ``written`` ack -- after which the slot is already free
    worker-side, so the later ``release()`` no-ops."""

    __slots__ = ("_pool", "_shard", "_slot", "_name", "_consumed")

    def __init__(self, pool: "ShardPool", shard: int, slot: int, name: str):
        self._pool = pool
        self._shard = shard
        self._slot = slot
        self._name = name
        self._consumed = False

    @property
    def released(self) -> bool:
        return self._consumed

    def release(self) -> None:
        if self._consumed:
            return
        self._consumed = True
        self._pool._drop_slot(self._shard, self._slot)

    async def remote_write(self, idx: int) -> None:
        if self._consumed:
            raise ConnectionError("slot lease already released")
        self._consumed = True
        await self._pool._remote_write(self._shard, self._slot, self._name, idx)


class ShardPool:
    """Supervisor for the seed-serve worker processes. One per scheduler;
    all methods run on the scheduler's event loop."""

    def __init__(
        self,
        size: int,
        *,
        churn_idle_seconds: float = 4.0,
        on_conn_closed: ConnClosedFn | None = None,
        component: str = "p2p",
        leech: bool = False,
        ring_slots: int = 32,
        slot_bytes: int = 1 << 20,
    ):
        self._target = max(0, size)
        self.churn_idle = churn_idle_seconds
        self._on_conn_closed = on_conn_closed or (lambda desc, r, m: None)
        self.component = component
        # Leech mode: workers run download pumps, each with a pre-fork
        # shared SlabRing of ``ring_slots`` x ``slot_bytes``-class slots.
        self.leech = leech
        self._ring_slots = max(1, ring_slots)
        # Normalize to the SlabRing's power-of-two slot class so the
        # scheduler's piece-length gate compares against the size the
        # ring actually allocates.
        self._slot_bytes = _bufpool_class_for(max(1, slot_bytes))
        self._prefix = "leech_shard" if leech else "data_plane_shard"
        # cid -> LeechConnProxy for handed-off download conns; their
        # closed verdicts route to the proxy (the dispatcher owns the
        # bookkeeping), NOT the seed plane's on_conn_closed callback.
        self._proxies: dict[int, object] = {}
        # (shard, slot) -> future resolved by the worker's written ack.
        self._pending_writes: dict[tuple[int, int], asyncio.Future] = {}
        # Parent-side mirror of outstanding slot leases (leak audit).
        self.slot_leases = 0
        self._workers: dict[int, _Worker] = {}
        self._conns: dict[int, tuple[int, dict]] = {}  # cid -> (shard, desc)
        self._next_cid = 0
        self._stopping = False
        self.lameduck = False
        self._reap_tasks: set[asyncio.Task] = set()
        from kraken_tpu.utils.metrics import REGISTRY

        self._c_handoffs = REGISTRY.counter(
            "data_plane_handoffs_total",
            "Seed conns handed to worker shards, by shard",
        )
        self._c_fallbacks = REGISTRY.counter(
            "data_plane_handoff_fallbacks_total",
            "Seed conns kept on the main loop (no shard could take them)",
        )
        self._c_crashes = REGISTRY.counter(
            "data_plane_worker_crashes_total",
            "Worker shards that exited without being asked to",
        )
        self._g_workers = REGISTRY.gauge(
            "data_plane_workers", "Configured seed-serve worker processes"
        )
        self._g_alive = REGISTRY.gauge(
            "data_plane_workers_alive", "Live seed-serve worker processes"
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for shard in range(self._target):
            self._spawn(shard)
        self._g_workers.set(self._target, component=self.component)

    def _spawn(self, shard: int) -> None:
        parent_sock, child_sock = socket.socketpair(
            socket.AF_UNIX, socket.SOCK_SEQPACKET
        )
        # The ring MUST exist before the fork: both processes inherit
        # the same anonymous MAP_SHARED pages. A respawned shard gets a
        # fresh ring (the dead worker's free-list state is gone with
        # it); old in-flight views pin the old mapping until they die.
        ring = (
            SlabRing(self._ring_slots, self._slot_bytes)
            if self.leech else None
        )
        ctx = multiprocessing.get_context("fork")
        proc = ctx.Process(
            target=_worker_main,
            args=(
                child_sock, parent_sock.fileno(), shard,
                {"churn_idle": self.churn_idle}, ring,
            ),
            daemon=True,  # backstop: never outlive the node process
            name=f"kraken-{'leech' if self.leech else 'data-plane'}-shard{shard}",
        )
        proc.start()
        child_sock.close()
        parent_sock.setblocking(False)
        w = _Worker(shard, proc, parent_sock, prefix=self._prefix, ring=ring)
        self._workers[shard] = w
        asyncio.get_running_loop().add_reader(
            parent_sock.fileno(), self._on_worker_msg, shard
        )
        self._g_alive.set(self.alive_workers, component=self.component)
        _log.info(
            "data-plane shard spawned",
            extra={"shard": shard, "pid": proc.pid},
        )

    def resize(self, size: int) -> None:
        """SIGHUP live resize: grow spawns fresh shards; shrink retires
        the highest shards -- they finish in-flight serves, close their
        conns, and exit; their slots release through the normal closed
        verdicts."""
        size = max(0, size)
        self._target = size
        self._g_workers.set(size, component=self.component)
        live = sorted(
            s for s, w in self._workers.items() if not w.retiring
        )
        for shard in range(size):
            if shard not in self._workers:
                self._spawn(shard)
        for shard in live:
            if shard >= size:
                w = self._workers[shard]
                w.retiring = True
                self._send(w, {"t": "stop"})

    def enter_lameduck(self) -> None:
        self.lameduck = True
        for w in self._workers.values():
            self._send(w, {"t": "lameduck"})

    def evict(self, name_hex: str) -> None:
        """A blob left the store (eviction, quarantine, unseed): every
        shard drops its fd and closes that torrent's conns gracefully."""
        for w in self._workers.values():
            self._send(w, {"t": "evict", "name": name_hex})

    def reconfigure(self, churn_idle_seconds: float) -> None:
        self.churn_idle = churn_idle_seconds
        for w in self._workers.values():
            self._send(w, {"t": "cfg", "churn_idle": churn_idle_seconds})

    async def stop(self) -> None:
        """Graceful teardown: ask every worker to drain, join with a
        bound, hard-kill stragglers, release any conn slots still
        attributed to shards. Reaps every child -- zero orphans is the
        soak harness's audit line."""
        self._stopping = True
        workers = list(self._workers.values())
        self._workers.clear()
        loop = asyncio.get_running_loop()
        for w in workers:
            try:
                loop.remove_reader(w.sock.fileno())
            except (OSError, ValueError):
                pass
            self._send(w, {"t": "stop"})

        def _join_all() -> None:
            deadline = time.monotonic() + 3.0
            for w in workers:
                w.proc.join(max(0.1, deadline - time.monotonic()))
            for w in workers:
                if w.proc.is_alive():
                    w.proc.terminate()
                    w.proc.join(1.0)
                if w.proc.is_alive():  # pragma: no cover - last resort
                    w.proc.kill()
                    w.proc.join(1.0)

        await asyncio.to_thread(_join_all)
        for w in workers:
            try:
                w.sock.close()
            except OSError:
                pass
            try:
                w.proc.close()  # releases the mp sentinel fd
            except (OSError, ValueError):  # pragma: no cover
                pass  # close() raises ValueError while still alive
        for cid, (shard, desc) in list(self._conns.items()):
            self._conns.pop(cid, None)
            proxy = self._proxies.pop(cid, None)
            if proxy is not None:
                proxy.on_remote_closed("pool_stop", False)
            else:
                self._safe_conn_closed(desc, "pool_stop", False)
        for key, fut in list(self._pending_writes.items()):
            self._pending_writes.pop(key, None)
            if not fut.done():
                fut.set_exception(ConnectionError("pool stopped mid-write"))
        for w in workers:
            if w.ring is not None:
                w.ring.close()
        self._g_alive.set(0, component=self.component)
        for t in list(self._reap_tasks):
            t.cancel()
        if self._reap_tasks:
            await asyncio.gather(*self._reap_tasks, return_exceptions=True)

    # -- handoff -----------------------------------------------------------

    @property
    def can_accept(self) -> bool:
        return (
            not self._stopping
            and not self.lameduck
            and any(not w.retiring for w in self._workers.values())
        )

    @property
    def num_conns(self) -> int:
        """Live handed-off conns -- counted into the scheduler's drain
        quiesce signal."""
        return len(self._conns)

    @property
    def slot_bytes(self) -> int:
        """Ring slot class in bytes (power of two). A leech handoff is
        only legal when the torrent's piece length fits one slot."""
        return self._slot_bytes

    def try_handoff(self, fd: int, desc: dict, proxy=None) -> bool:
        """Ship a handshaken conn (by fd) to the least-loaded shard.
        False = no shard could take it right now (all retiring, control
        channel backlogged); the caller keeps the conn on the main loop.

        ``proxy`` (leech mode): the :class:`LeechConnProxy` the
        dispatcher will drive. On success it is bound to the worker --
        its outbound frames and close flow through :meth:`send_frames` /
        :meth:`close_remote`, and the worker's verdicts route back to
        it."""
        if not self.can_accept:
            self._c_fallbacks.inc()
            return False
        cid = self._next_cid
        self._next_cid += 1
        payload = msgpack.packb({"t": "conn", "cid": cid, **desc})
        candidates = sorted(
            (w for w in self._workers.values() if not w.retiring),
            key=lambda w: w.conns,
        )
        for w in candidates:
            try:
                socket.send_fds(w.sock, [payload], [fd])
            except (BlockingIOError, OSError):
                continue
            w.conns += 1
            self._conns[cid] = (w.shard, desc)
            if proxy is not None:
                proxy._shard_cid = cid
                self._proxies[cid] = proxy
            self._c_handoffs.inc(shard=w.label)
            return True
        self._c_fallbacks.inc()
        return False

    # -- leech proxy plumbing ----------------------------------------------

    def send_frames(self, proxy, frames: list) -> None:
        """Outbound frames for a handed-off leech conn (injected into
        the proxy as its ``send_frames``). Best-effort, like every
        control-channel send: a lost frame behaves like a lossy peer
        (requests re-issue on piece timeout)."""
        cid = getattr(proxy, "_shard_cid", None)
        entry = self._conns.get(cid) if cid is not None else None
        if entry is None:
            return
        w = self._workers.get(entry[0])
        if w is not None:
            self._send(w, {"t": "send", "cid": cid, "frames": frames})

    def close_remote(self, proxy, reason: str, mis: bool) -> None:
        """Parent-initiated close of a handed-off leech conn."""
        cid = getattr(proxy, "_shard_cid", None)
        entry = self._conns.get(cid) if cid is not None else None
        if entry is None:
            return
        w = self._workers.get(entry[0])
        if w is not None:
            self._send(w, {"t": "close", "cid": cid})

    def _drop_slot(self, shard: int, slot: int) -> None:
        """A slot lease released unconsumed (corrupt piece, duplicate,
        teardown): tell the worker to free it without writing."""
        self.slot_leases = max(0, self.slot_leases - 1)
        w = self._workers.get(shard)
        if w is not None:
            self._send(w, {"t": "drop", "slot": slot})
        # Worker gone: its ring (and authoritative free list) died with
        # it -- nothing to free.

    async def _remote_write(self, shard: int, slot: int, name: str,
                            idx: int) -> None:
        """Good-digest verdict: have the worker pwrite the slot, await
        its written ack. Raising (worker death, write error, timeout)
        leaves the piece unmarked -- the dispatcher requeues it."""
        self.slot_leases = max(0, self.slot_leases - 1)
        w = self._workers.get(shard)
        if w is None or not w.proc.is_alive():
            raise ConnectionError("leech worker exited before write")
        fut = asyncio.get_running_loop().create_future()
        self._pending_writes[(shard, slot)] = fut
        self._send(w, {"t": "write", "name": name, "slot": slot, "idx": idx})
        try:
            # Generous bound: a wedged worker must not strand the
            # dispatcher's payload task forever (its conn would never
            # churn -- receiving>0 exempts it).
            await asyncio.wait_for(fut, 30.0)
        except asyncio.TimeoutError:
            raise ConnectionError(
                f"leech shard {shard}: written ack timed out (piece {idx})"
            ) from None
        finally:
            self._pending_writes.pop((shard, slot), None)

    # -- worker messages ---------------------------------------------------

    def _send(self, w: _Worker, msg: dict) -> None:
        try:
            w.sock.send(msgpack.packb(msg))
        except (BlockingIOError, OSError):
            pass  # worker gone or backlogged; EOF handling catches death

    def _on_worker_msg(self, shard: int) -> None:
        w = self._workers.get(shard)
        if w is None:
            return
        while True:
            try:
                data = w.sock.recv(_CTRL_RECV)
            except BlockingIOError:
                return
            except OSError:
                data = b""
            if not data:
                self._worker_gone(shard)
                return
            try:
                self._handle_worker_msg(w, msgpack.unpackb(data))
            except Exception:
                _log.exception("bad message from shard %d", shard)

    def _handle_worker_msg(self, w: _Worker, msg: dict) -> None:
        t = msg.get("t")
        if t == "stats":
            from kraken_tpu.utils.metrics import record_data_plane_shard

            w.cpu_s = float(msg.get("cpu_s", 0.0))
            record_data_plane_shard(
                w.label,
                conns=msg.get("conns", 0),
                bytes_delta=max(0, msg.get("bytes_up", 0) - w.last_bytes),
                serves_delta=max(0, msg.get("serves", 0) - w.last_serves),
                cpu_seconds=w.cpu_s,
                bytes_down_delta=max(
                    0, msg.get("bytes_down", 0) - w.last_down
                ),
                pieces_delta=max(0, msg.get("pieces", 0) - w.last_pieces),
            )
            w.last_bytes = msg.get("bytes_up", w.last_bytes)
            w.last_serves = msg.get("serves", w.last_serves)
            w.last_down = msg.get("bytes_down", w.last_down)
            w.last_pieces = msg.get("pieces", w.last_pieces)
        elif t == "closed":
            cid = msg.get("cid")
            entry = self._conns.pop(cid, None)
            w.conns = max(0, w.conns - 1)
            proxy = self._proxies.pop(cid, None)
            if proxy is not None:
                # Dispatcher-owned conn: the verdict flows through the
                # proxy (misbehavior intact -> blacklist escalation);
                # connstate/event cleanup rides its closed callback.
                proxy.on_remote_closed(
                    msg.get("reason", ""), bool(msg.get("mis"))
                )
            elif entry is not None:
                _shard, desc = entry
                self._safe_conn_closed(
                    desc, msg.get("reason", ""), bool(msg.get("mis"))
                )
        elif t == "piece":
            self._on_piece(w, msg)
        elif t == "written":
            fut = self._pending_writes.get((w.shard, msg.get("slot")))
            if fut is not None and not fut.done():
                if msg.get("ok"):
                    fut.set_result(None)
                else:
                    fut.set_exception(
                        OSError(f"leech shard {w.shard}: pwrite failed")
                    )
        elif t == "frame":
            proxy = self._proxies.get(msg.get("cid"))
            if proxy is not None:
                proxy.on_frame(
                    msg.get("mt"), msg.get("h") or {}, msg.get("p") or b""
                )
        elif t == "spans":
            # Worker serve spans come home: adopt them so the parent's
            # /debug/trace and flight-recorder dumps hold the WHOLE
            # data plane, forked halves included.
            trace.TRACER.record_foreign(msg.get("spans") or [])
        elif t == "prof":
            # Folded-stack deltas from the shard's own sampler: one
            # /debug/pprof/profile (and one flame collapse) covers the
            # main loop AND the forked serve plane.
            from kraken_tpu.utils import profiler

            profiler.PROFILER.record_foreign(
                str(msg.get("node") or w.label),
                msg.get("stacks") or [],
                msg.get("planes") or {},
            )
        elif t == "ready":
            pass

    def _on_piece(self, w: _Worker, msg: dict) -> None:
        """A leech worker landed a complete piece in its ring: build the
        zero-copy Message (payload = a view of the shared mapping, lease
        = the slot) and deliver it to the owning proxy exactly like the
        recv loop's payload-handler bypass."""
        cid, slot, ln = msg.get("cid"), msg.get("slot"), msg.get("ln", 0)
        idx = msg.get("idx")
        entry = self._conns.get(cid)
        proxy = self._proxies.get(cid)
        if w.ring is None or not isinstance(slot, int):
            return
        lease = _SlotLease(
            self, w.shard, slot,
            entry[1].get("name", "") if entry else "",
        )
        self.slot_leases += 1
        if proxy is None or not isinstance(idx, int):
            lease.release()  # conn already gone: free the slot
            return
        m = Message(
            MsgType.PIECE_PAYLOAD, {"index": idx},
            w.ring.view(slot, ln), lease=lease,
        )
        try:
            proxy.deliver_payload(m)
        except Exception:
            m.release()
            _log.exception("leech payload delivery failed")

    def _safe_conn_closed(self, desc: dict, reason: str, mis: bool) -> None:
        try:
            self._on_conn_closed(desc, reason, mis)
        except Exception:
            _log.exception("shard conn-closed callback failed")

    def _worker_gone(self, shard: int) -> None:
        w = self._workers.pop(shard, None)
        if w is None:
            return
        loop = asyncio.get_running_loop()
        try:
            loop.remove_reader(w.sock.fileno())
        except (OSError, ValueError):
            pass
        try:
            w.sock.close()
        except OSError:
            pass
        # Every conn this shard held is gone with it: release the slots
        # so the remotes can redial (onto another shard or the main loop).
        for cid, (s, desc) in list(self._conns.items()):
            if s == shard:
                self._conns.pop(cid, None)
                proxy = self._proxies.pop(cid, None)
                if proxy is not None:
                    # No blacklist: worker death is OUR fault, not the
                    # peer's -- the dispatcher drops + requeues.
                    proxy.on_remote_closed("worker_exit", False)
                else:
                    self._safe_conn_closed(desc, "worker_exit", False)
        # In-flight verdict writes can never be acked now: fail them so
        # write_piece raises, the piece stays unmarked, and it requeues.
        for key, fut in list(self._pending_writes.items()):
            if key[0] == shard:
                self._pending_writes.pop(key, None)
                if not fut.done():
                    fut.set_exception(
                        ConnectionError("leech worker exited mid-write")
                    )
        if w.ring is not None:
            # Best-effort unmap; in-flight views keep the pages alive
            # until verify finishes with them. The respawn maps fresh.
            w.ring.close()
        expected = w.retiring or self._stopping
        if not expected:
            self._c_crashes.inc(shard=w.label)
            _log.warning(
                "data-plane shard died unexpectedly; respawning",
                extra={"shard": shard, "pid": w.proc.pid},
            )

        def _reap_and_respawn() -> None:
            t = asyncio.create_task(self._reap(w, shard))
            self._reap_tasks.add(t)
            t.add_done_callback(self._reap_tasks.discard)

        _reap_and_respawn()
        self._g_alive.set(self.alive_workers, component=self.component)

    async def _reap(self, w: _Worker, shard: int) -> None:
        def _join() -> None:
            w.proc.join(2.0)
            if w.proc.is_alive():  # pragma: no cover
                w.proc.terminate()
                w.proc.join(1.0)

        await asyncio.to_thread(_join)
        try:
            w.proc.close()
        except (OSError, ValueError):  # pragma: no cover
            pass  # close() raises ValueError while still alive
        # Respawn on crash, but ALSO when a retiring shard exits while
        # the target has grown back over it (shrink-then-grow race: the
        # grow saw the old shard still in the table and spawned nothing,
        # so this exit is the only chance to restore the pool size).
        if (
            not self._stopping
            and shard < self._target
            and shard not in self._workers
        ):
            self._spawn(shard)

    # -- introspection (sentinel / tests) ----------------------------------

    @property
    def alive_workers(self) -> int:
        return sum(1 for w in self._workers.values() if w.proc.is_alive())

    @property
    def expected_workers(self) -> int:
        return self._target

    def worker_info(self) -> list[dict]:
        """Per-shard pid/liveness/conn snapshot for the resource sentinel
        (child fd+RSS aggregation, crash reap-check) and /debug surfaces."""
        return [
            {
                "shard": w.shard,
                "pid": w.proc.pid,
                "alive": w.proc.is_alive(),
                "retiring": w.retiring,
                "conns": w.conns,
                "cpu_s": w.cpu_s,
            }
            for w in sorted(self._workers.values(), key=lambda w: w.shard)
        ]

"""Piece selection: pending-request manager with timeouts and policies.

Mirrors uber/kraken ``lib/torrent/scheduler/dispatch/piecerequest``
(pending-request manager with timeout & retry; default and rarest-first
policies) -- upstream path, unverified; SURVEY.md SS2.2.
"""

from __future__ import annotations

import random
import time
from typing import Iterable

from kraken_tpu.core.peer import PeerID


class RequestManager:
    """Tracks which pieces are requested from which peers, with expiry.

    ``policy`` is ``"rarest_first"`` (default, as the reference's
    production policy) or ``"random"``. In endgame (every missing piece
    already requested) duplicate requests are allowed so one slow peer
    can't stall completion.
    """

    def __init__(
        self,
        policy: str = "rarest_first",
        timeout_seconds: float = 8.0,
        pipeline_limit: int = 4,
        endgame_duplication: int = 2,
    ):
        if policy not in ("rarest_first", "random"):
            raise ValueError(f"unknown piece policy: {policy!r}")
        self.policy = policy
        self.timeout = timeout_seconds
        self.pipeline_limit = pipeline_limit
        # Max outstanding requests per piece in endgame. Unbounded
        # duplication collapses large swarms: with P-deep pipelines over C
        # conns and few missing pieces, every piece gets requested from
        # every peer and the swarm's goodput divides by the redundancy
        # (measured: 100-agent flash crowd fell from ~85 to ~19 MB/s).
        self.endgame_duplication = endgame_duplication
        # piece -> {peer -> sent_ts}
        self._requests: dict[int, dict[PeerID, float]] = {}
        # EWMA of request->completion age: drives the ADAPTIVE stale
        # threshold for rescue duplicates. A fixed threshold cannot serve
        # both regimes: too low re-requests everything under load (the
        # duplication collapse above), too high parks stragglers for tens
        # of seconds behind one slow peer.
        self._service_ewma: float | None = None

    # -- bookkeeping -------------------------------------------------------

    def _expire(self, now: float) -> None:
        # Adaptive hard expiry: the configured timeout is a FLOOR. Under
        # load (large swarm, saturated seeder) honest service times exceed
        # any fixed timeout, and expiring in-flight work re-requests it --
        # a feedback loop that collapses goodput.
        cutoff = max(
            self.timeout,
            min(8.0 * (self._service_ewma or 0.0), 10.0 * self.timeout),
        )
        for piece, peers in list(self._requests.items()):
            for peer, ts in list(peers.items()):
                if now - ts > cutoff:
                    del peers[peer]
            if not peers:
                del self._requests[piece]

    def mark_sent(self, piece: int, peer: PeerID, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self._requests.setdefault(piece, {})[peer] = now

    def clear_piece(self, piece: int, now: float | None = None) -> None:
        peers = self._requests.pop(piece, None)
        if peers:
            now = time.monotonic() if now is None else now
            # NEWEST mark: the most recent request (often the rescue that
            # actually delivered) approximates true service time; the
            # oldest would fold abandoned-request ages into the EWMA and
            # ratchet the adaptive thresholds toward worst-case.
            age = now - max(peers.values())
            if age >= 0:
                self._service_ewma = (
                    age
                    if self._service_ewma is None
                    else 0.9 * self._service_ewma + 0.1 * age
                )

    def stale_after(self) -> float:
        """Age past which an in-flight request qualifies for a rescue
        duplicate: several observed service times, clamped into
        [0.25 s, timeout / 2]."""
        base = self._service_ewma if self._service_ewma is not None else 0.25
        return min(max(4.0 * base, 0.25), self.timeout * 0.5)

    def clear_peer(self, peer: PeerID) -> None:
        for piece, peers in list(self._requests.items()):
            peers.pop(peer, None)
            if not peers:
                del self._requests[piece]

    def pending_for(self, peer: PeerID, now: float | None = None) -> list[int]:
        now = time.monotonic() if now is None else now
        self._expire(now)
        return [p for p, peers in self._requests.items() if peer in peers]

    # -- selection ---------------------------------------------------------

    def select(
        self,
        peer: PeerID,
        peer_has: set[int],
        missing: Iterable[int],
        availability: dict[int, int],
        now: float | None = None,
    ) -> list[int]:
        """Pieces to request from ``peer`` now, respecting the pipeline
        limit. ``availability[piece]`` = number of connected peers holding
        it (drives rarest-first)."""
        now = time.monotonic() if now is None else now
        self._expire(now)

        budget = self.pipeline_limit - len(self.pending_for(peer, now))
        if budget <= 0:
            return []

        missing = list(missing)
        fresh = [
            p for p in missing if p in peer_has and p not in self._requests
        ]
        if not fresh:
            # Endgame: everything missing is in flight somewhere. With deep
            # pipelines that is the NORMAL mid-download state, so duplicate
            # only to rescue requests that have gone stale (a slow peer),
            # bounded per piece -- otherwise every piece is fetched from
            # every conn and swarm goodput divides by the redundancy.
            stale_after = self.stale_after()
            fresh = [
                p
                for p in missing
                if p in peer_has
                and peer not in self._requests.get(p, {})
                and len(self._requests.get(p, {})) < self.endgame_duplication
                and now - max(self._requests.get(p, {}).values(), default=0.0)
                > stale_after
            ]
        if self.policy == "rarest_first":
            fresh.sort(key=lambda p: (availability.get(p, 0), random.random()))
        else:
            random.shuffle(fresh)
        chosen = fresh[:budget]
        for p in chosen:
            self.mark_sent(p, peer, now)
        return chosen

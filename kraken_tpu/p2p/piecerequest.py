"""Piece selection: pending-request manager with timeouts and policies.

Mirrors uber/kraken ``lib/torrent/scheduler/dispatch/piecerequest``
(pending-request manager with timeout & retry; default and rarest-first
policies) -- upstream path, unverified; SURVEY.md SS2.2.
"""

from __future__ import annotations

import random
import time
from typing import Iterable

from kraken_tpu.core.peer import PeerID


class RequestManager:
    """Tracks which pieces are requested from which peers, with expiry.

    ``policy`` is ``"rarest_first"`` (default, as the reference's
    production policy) or ``"random"``. In endgame (every missing piece
    already requested) duplicate requests are allowed so one slow peer
    can't stall completion.
    """

    def __init__(
        self,
        policy: str = "rarest_first",
        timeout_seconds: float = 8.0,
        pipeline_limit: int = 4,
    ):
        if policy not in ("rarest_first", "random"):
            raise ValueError(f"unknown piece policy: {policy!r}")
        self.policy = policy
        self.timeout = timeout_seconds
        self.pipeline_limit = pipeline_limit
        # piece -> {peer -> sent_ts}
        self._requests: dict[int, dict[PeerID, float]] = {}

    # -- bookkeeping -------------------------------------------------------

    def _expire(self, now: float) -> None:
        for piece, peers in list(self._requests.items()):
            for peer, ts in list(peers.items()):
                if now - ts > self.timeout:
                    del peers[peer]
            if not peers:
                del self._requests[piece]

    def mark_sent(self, piece: int, peer: PeerID, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self._requests.setdefault(piece, {})[peer] = now

    def clear_piece(self, piece: int) -> None:
        self._requests.pop(piece, None)

    def clear_peer(self, peer: PeerID) -> None:
        for piece, peers in list(self._requests.items()):
            peers.pop(peer, None)
            if not peers:
                del self._requests[piece]

    def pending_for(self, peer: PeerID, now: float | None = None) -> list[int]:
        now = time.monotonic() if now is None else now
        self._expire(now)
        return [p for p, peers in self._requests.items() if peer in peers]

    # -- selection ---------------------------------------------------------

    def select(
        self,
        peer: PeerID,
        peer_has: set[int],
        missing: Iterable[int],
        availability: dict[int, int],
        now: float | None = None,
    ) -> list[int]:
        """Pieces to request from ``peer`` now, respecting the pipeline
        limit. ``availability[piece]`` = number of connected peers holding
        it (drives rarest-first)."""
        now = time.monotonic() if now is None else now
        self._expire(now)

        budget = self.pipeline_limit - len(self.pending_for(peer, now))
        if budget <= 0:
            return []

        missing = list(missing)
        fresh = [
            p for p in missing if p in peer_has and p not in self._requests
        ]
        if not fresh:
            # Endgame: everything missing is in flight somewhere; duplicate
            # requests to this peer for pieces it holds but isn't serving.
            fresh = [
                p
                for p in missing
                if p in peer_has and peer not in self._requests.get(p, {})
            ]
        if self.policy == "rarest_first":
            fresh.sort(key=lambda p: (availability.get(p, 0), random.random()))
        else:
            random.shuffle(fresh)
        chosen = fresh[:budget]
        for p in chosen:
            self.mark_sent(p, peer, now)
        return chosen

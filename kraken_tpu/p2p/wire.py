"""P2P wire protocol: length-prefixed frames, msgpack headers, raw payloads.

Message set mirrored from uber/kraken ``proto/p2p/p2p.proto`` (BITFIELD,
PIECE_REQUEST, PIECE_PAYLOAD, ANNOUNCE_PIECE, CANCEL_PIECE, COMPLETE,
ERROR; piece bytes framed after the message) -- upstream path, unverified;
SURVEY.md SS2.2. Framing is hand-rolled rather than protobuf: a fixed
9-byte prefix + msgpack header keeps zero codegen and lets the payload ride
as one contiguous slice (no protobuf copy of 4 MiB pieces).

Frame layout (all ints big-endian):

    u8  type | u32 header_len | u32 payload_len | header | payload

Handshake exchange happens first on every conn, as HANDSHAKE frames.

Zero-copy recv (round 7): with a :class:`~kraken_tpu.utils.bufpool.
BufferPool`, PIECE_PAYLOAD bytes are read straight into a leased,
recycled buffer -- no per-piece payload allocation and no
``raw[header_len:]`` slice copy -- and ``Message.payload`` is a writable
``memoryview`` that flows through verify and ``os.pwrite`` untouched.
The lease rides on ``Message.lease``; whoever consumes the payload calls
:meth:`Message.release` exactly once (idempotent) after the last read.

Corked vectored send: :func:`send_messages` writes a whole batch of
frames with ONE ``drain()`` -- control frames coalesce into a single
``writelines`` buffer, payloads are appended without an extra copy --
so the send loop pays the event-loop future machinery per batch, not
per frame.
"""

from __future__ import annotations

import asyncio
import enum
from typing import Any, Iterable, Optional

import msgpack

from kraken_tpu.utils.bufpool import BufferPool, Lease

MAX_HEADER = 1 << 20
MAX_PAYLOAD = 1 << 26  # 64 MiB -- piece length upper bound

# Control frames below this ride in the coalesced writelines buffer (one
# small concat beats N transport appends); payloads at or above it are
# handed to the transport as-is, avoiding a batch-sized join copy.
_COALESCE_CUTOFF = 16 << 10


class MsgType(enum.IntEnum):
    HANDSHAKE = 0
    BITFIELD = 1
    PIECE_REQUEST = 2
    PIECE_PAYLOAD = 3
    ANNOUNCE_PIECE = 4
    CANCEL_PIECE = 5
    COMPLETE = 6
    ERROR = 7
    PEER_EXCHANGE = 8


class WireError(Exception):
    pass


class PayloadOversizeError(WireError):
    """A PIECE_PAYLOAD frame longer than the handshaken torrent's piece
    length (or the absolute MAX_PAYLOAD cap). Raised BEFORE the payload
    is buffered, so a hostile peer cannot balloon RSS; the conn plane
    treats it as misbehavior (escalating blacklist), not connectivity."""


class Message:
    """One protocol frame: typed header dict + optional raw payload.

    ``payload`` is ``bytes`` for control frames and (on the pooled recv
    path) a ``memoryview`` into a leased buffer for PIECE_PAYLOAD;
    ``release()`` returns that buffer to its pool and is a no-op for
    unpooled messages, so consumers call it unconditionally."""

    __slots__ = ("type", "header", "payload", "lease")

    def __init__(
        self,
        type: MsgType,
        header: dict | None = None,
        payload: bytes | memoryview = b"",
        lease: Optional[Lease] = None,
    ):
        self.type = type
        self.header = header or {}
        self.payload = payload
        self.lease = lease

    def release(self) -> None:
        lease, self.lease = self.lease, None
        if lease is not None:
            # The view dies with the lease; drop our reference first so a
            # late reader gets b"" length math, not a released-view error.
            self.payload = b""
            lease.release()

    def __repr__(self) -> str:
        return f"Message({self.type.name}, {self.header}, payload={len(self.payload)}B)"

    # -- constructors for each message of the set --------------------------

    @classmethod
    def handshake(
        cls, peer_id: str, info_hash: str, name: str, namespace: str,
        bitfield: bytes, num_pieces: int, traceparent: str = "",
        listen_port: int = 0,
    ) -> "Message":
        """``name`` is the blob digest hex -- carried alongside the info
        hash so the accepting side can load its stored metainfo directly
        (no reverse info-hash index needed). ``traceparent`` (dial side
        only) lets the accepting node's serve spans join the dialer's
        trace (utils/trace.py); absent for peers without an active
        trace. ``listen_port`` is this side's p2p LISTEN port (an inbound
        conn's transport port is ephemeral) -- it gives the remote a
        dialable addr to gossip over PEX; 0 omits the key (older peers
        tolerate its absence the same way)."""
        header = {
            "peer_id": peer_id,
            "info_hash": info_hash,
            "name": name,
            "namespace": namespace,
            "num_pieces": num_pieces,
        }
        if traceparent:
            header["tp"] = traceparent
        if listen_port:
            header["lp"] = listen_port
        return cls(MsgType.HANDSHAKE, header, payload=bitfield)

    @classmethod
    def bitfield(cls, bits: bytes, num_pieces: int) -> "Message":
        return cls(MsgType.BITFIELD, {"num_pieces": num_pieces}, payload=bits)

    @classmethod
    def piece_request(cls, index: int, traceparent: str | None = None) -> "Message":
        """``traceparent`` joins the request to the leecher's SAMPLED
        trace, so the remote's serve span (dispatcher or shardpool
        worker) lands in the same tree; omitted on unsampled traces --
        the serve side then creates no span at all."""
        header: dict = {"index": index}
        if traceparent:
            header["tp"] = traceparent
        return cls(MsgType.PIECE_REQUEST, header)

    @classmethod
    def piece_payload(cls, index: int, data: bytes) -> "Message":
        return cls(MsgType.PIECE_PAYLOAD, {"index": index}, payload=data)

    @classmethod
    def announce_piece(cls, index: int) -> "Message":
        return cls(MsgType.ANNOUNCE_PIECE, {"index": index})

    @classmethod
    def cancel_piece(cls, index: int) -> "Message":
        return cls(MsgType.CANCEL_PIECE, {"index": index})

    @classmethod
    def complete(cls) -> "Message":
        return cls(MsgType.COMPLETE)

    @classmethod
    def error(cls, code: str, detail: str = "") -> "Message":
        return cls(MsgType.ERROR, {"code": code, "detail": detail})

    @classmethod
    def peer_exchange(cls, added: list[dict], dropped: list[str]) -> "Message":
        """Gossip frame (PEX): compact per-torrent peer deltas riding an
        existing conn. ``added`` entries are dicts with short keys --
        ``id`` (peer id hex), ``ip``, ``p`` (listen port), ``o`` (origin
        flag, omitted when false) -- ``dropped`` is peer id hexes the
        sender no longer has conns to. The torrent is implied by the conn
        the frame rides on (conns are per-info-hash)."""
        return cls(MsgType.PEER_EXCHANGE, {"a": added, "d": dropped})


def frame_head(mtype: int, header: bytes, payload_len: int) -> bytes:
    """The 9-byte prefix + packed header of one frame -- the single
    definition of the wire layout. Shared by the stream send path here
    and the shardpool workers' raw-socket paths (seed serves and the
    leech plane's parent-authored control frames), so the framing can
    never skew between the main loop and the forked halves."""
    return (
        bytes([mtype])
        + len(header).to_bytes(4, "big")
        + payload_len.to_bytes(4, "big")
        + header
    )


def frame_bytes(mtype: int, header: dict, payload: bytes = b"") -> bytes:
    """One fully-encoded frame from its parts (control frames only --
    payload rides inline, so callers keep it small)."""
    packed = msgpack.packb(header)
    return frame_head(mtype, packed, len(payload)) + payload


def _head(msg: Message, header: bytes) -> bytes:
    return frame_head(msg.type, header, len(msg.payload))


async def send_messages(
    writer: asyncio.StreamWriter, msgs: Iterable[Message]
) -> None:
    """Write every frame in ``msgs`` and drain ONCE.

    Small frames (prefix+header, control payloads) collect into one
    ``writelines`` call -- a single transport append for the whole run of
    control traffic riding a payload batch. Piece payloads are written
    as-is: the transport buffers the existing bytes/memoryview, so the
    batch costs zero payload copies on this side of the socket.
    """
    small: list[bytes] = []
    for msg in msgs:
        header = msgpack.packb(msg.header)
        small.append(_head(msg, header))
        payload = msg.payload
        if payload:
            if len(payload) < _COALESCE_CUTOFF:
                small.append(bytes(payload))
            else:
                if small:
                    writer.writelines(small)
                    small = []
                writer.write(payload)
    if small:
        writer.writelines(small)
    await writer.drain()


async def send_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    await send_messages(writer, (msg,))


async def _readinto_exactly(
    reader: asyncio.StreamReader, view: memoryview
) -> None:
    """``readexactly`` into a caller-owned buffer.

    asyncio's StreamReader has no public readinto, and ``readexactly``
    materializes a fresh payload-sized ``bytes`` per call -- the exact
    per-piece allocation the bufpool exists to remove. This drains the
    reader's internal buffer straight into ``view`` using the same
    private fields ``readexactly`` itself uses (``_buffer``, ``_eof``,
    ``_wait_for_data``, ``_maybe_resume_transport`` -- stable across
    CPython 3.8-3.12); if an exotic reader lacks them we fall back to
    readexactly + copy (correct, one transient allocation).
    """
    n = len(view)
    if not (
        hasattr(reader, "_buffer")
        and hasattr(reader, "_eof")
        and hasattr(reader, "_wait_for_data")
        and hasattr(reader, "_maybe_resume_transport")
    ):  # pragma: no cover - non-CPython readers
        view[:] = await reader.readexactly(n)
        return
    pos = 0
    while pos < n:
        exc = reader.exception()
        if exc is not None:
            raise exc
        if reader._buffer:
            take = min(len(reader._buffer), n - pos)
            with memoryview(reader._buffer) as mv:
                view[pos : pos + take] = mv[:take]
            del reader._buffer[:take]
            reader._maybe_resume_transport()
            pos += take
        elif reader._eof:
            raise asyncio.IncompleteReadError(bytes(view[:pos]), n)
        else:
            await reader._wait_for_data("_readinto_exactly")


async def recv_message(
    reader: asyncio.StreamReader,
    pool: Optional[BufferPool] = None,
    max_payload: int = MAX_PAYLOAD,
) -> Message:
    """Read one frame. With ``pool``, PIECE_PAYLOAD bytes land in a
    leased buffer (``Message.payload`` is a memoryview, ``Message.lease``
    owns the return); without, behavior matches the classic bytes path.

    ``max_payload`` tightens the PIECE_PAYLOAD bound to the handshaken
    torrent's piece length; violations raise :class:`PayloadOversizeError`
    BEFORE any payload byte is buffered.
    """
    try:
        prefix = await reader.readexactly(9)
    except asyncio.IncompleteReadError as e:
        raise WireError("connection closed") from e
    mtype = prefix[0]
    header_len = int.from_bytes(prefix[1:5], "big")
    payload_len = int.from_bytes(prefix[5:9], "big")
    try:
        t = MsgType(mtype)
    except ValueError:
        raise WireError(f"unknown message type {mtype}") from None
    if t == MsgType.PIECE_PAYLOAD and payload_len > min(max_payload, MAX_PAYLOAD):
        raise PayloadOversizeError(
            f"piece payload {payload_len} exceeds limit "
            f"{min(max_payload, MAX_PAYLOAD)}"
        )
    if header_len > MAX_HEADER or payload_len > MAX_PAYLOAD:
        raise WireError(f"oversized frame: header={header_len} payload={payload_len}")
    try:
        raw_header = await reader.readexactly(header_len) if header_len else b""
    except asyncio.IncompleteReadError as e:
        raise WireError("connection closed mid-frame") from e
    try:
        header: Any = msgpack.unpackb(raw_header) if header_len else {}
    except Exception as e:
        # msgpack surfaces corruption as several exception types (its own
        # unpack errors, UnicodeDecodeError for non-utf8 raw strings,
        # ValueError for depth/size) -- all of them are one thing to the
        # conn plane: a malformed frame from a bad peer.
        raise WireError(f"malformed header: {e}") from e
    if not isinstance(header, dict):
        raise WireError("malformed header")
    lease: Optional[Lease] = None
    if payload_len == 0:
        payload: bytes | memoryview = b""
    elif pool is not None and t == MsgType.PIECE_PAYLOAD:
        lease = pool.lease(payload_len)
        try:
            await _readinto_exactly(reader, lease.view)
        except asyncio.IncompleteReadError as e:
            lease.release()
            raise WireError("connection closed mid-frame") from e
        except BaseException:
            lease.release()
            raise
        payload = lease.view
    else:
        try:
            payload = await reader.readexactly(payload_len)
        except asyncio.IncompleteReadError as e:
            raise WireError("connection closed mid-frame") from e
    return Message(t, header, payload, lease=lease)

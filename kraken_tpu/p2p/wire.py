"""P2P wire protocol: length-prefixed frames, msgpack headers, raw payloads.

Message set mirrored from uber/kraken ``proto/p2p/p2p.proto`` (BITFIELD,
PIECE_REQUEST, PIECE_PAYLOAD, ANNOUNCE_PIECE, CANCEL_PIECE, COMPLETE,
ERROR; piece bytes framed after the message) -- upstream path, unverified;
SURVEY.md SS2.2. Framing is hand-rolled rather than protobuf: a fixed
9-byte prefix + msgpack header keeps zero codegen and lets the payload ride
as one contiguous slice (no protobuf copy of 4 MiB pieces).

Frame layout (all ints big-endian):

    u8  type | u32 header_len | u32 payload_len | header | payload

Handshake exchange happens first on every conn, as HANDSHAKE frames.
"""

from __future__ import annotations

import asyncio
import enum
from typing import Any

import msgpack

MAX_HEADER = 1 << 20
MAX_PAYLOAD = 1 << 26  # 64 MiB -- piece length upper bound


class MsgType(enum.IntEnum):
    HANDSHAKE = 0
    BITFIELD = 1
    PIECE_REQUEST = 2
    PIECE_PAYLOAD = 3
    ANNOUNCE_PIECE = 4
    CANCEL_PIECE = 5
    COMPLETE = 6
    ERROR = 7


class WireError(Exception):
    pass


class Message:
    """One protocol frame: typed header dict + optional raw payload."""

    __slots__ = ("type", "header", "payload")

    def __init__(self, type: MsgType, header: dict | None = None, payload: bytes = b""):
        self.type = type
        self.header = header or {}
        self.payload = payload

    def __repr__(self) -> str:
        return f"Message({self.type.name}, {self.header}, payload={len(self.payload)}B)"

    # -- constructors for each message of the set --------------------------

    @classmethod
    def handshake(
        cls, peer_id: str, info_hash: str, name: str, namespace: str,
        bitfield: bytes, num_pieces: int,
    ) -> "Message":
        """``name`` is the blob digest hex -- carried alongside the info
        hash so the accepting side can load its stored metainfo directly
        (no reverse info-hash index needed)."""
        return cls(
            MsgType.HANDSHAKE,
            {
                "peer_id": peer_id,
                "info_hash": info_hash,
                "name": name,
                "namespace": namespace,
                "num_pieces": num_pieces,
            },
            payload=bitfield,
        )

    @classmethod
    def bitfield(cls, bits: bytes, num_pieces: int) -> "Message":
        return cls(MsgType.BITFIELD, {"num_pieces": num_pieces}, payload=bits)

    @classmethod
    def piece_request(cls, index: int) -> "Message":
        return cls(MsgType.PIECE_REQUEST, {"index": index})

    @classmethod
    def piece_payload(cls, index: int, data: bytes) -> "Message":
        return cls(MsgType.PIECE_PAYLOAD, {"index": index}, payload=data)

    @classmethod
    def announce_piece(cls, index: int) -> "Message":
        return cls(MsgType.ANNOUNCE_PIECE, {"index": index})

    @classmethod
    def cancel_piece(cls, index: int) -> "Message":
        return cls(MsgType.CANCEL_PIECE, {"index": index})

    @classmethod
    def complete(cls) -> "Message":
        return cls(MsgType.COMPLETE)

    @classmethod
    def error(cls, code: str, detail: str = "") -> "Message":
        return cls(MsgType.ERROR, {"code": code, "detail": detail})


async def send_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    header = msgpack.packb(msg.header)
    writer.write(
        bytes([msg.type])
        + len(header).to_bytes(4, "big")
        + len(msg.payload).to_bytes(4, "big")
    )
    writer.write(header)
    if msg.payload:
        writer.write(msg.payload)
    await writer.drain()


async def recv_message(reader: asyncio.StreamReader) -> Message:
    try:
        prefix = await reader.readexactly(9)
    except asyncio.IncompleteReadError as e:
        raise WireError("connection closed") from e
    mtype = prefix[0]
    header_len = int.from_bytes(prefix[1:5], "big")
    payload_len = int.from_bytes(prefix[5:9], "big")
    if header_len > MAX_HEADER or payload_len > MAX_PAYLOAD:
        raise WireError(f"oversized frame: header={header_len} payload={payload_len}")
    try:
        t = MsgType(mtype)
    except ValueError:
        raise WireError(f"unknown message type {mtype}") from None
    try:
        raw = await reader.readexactly(header_len + payload_len)
    except asyncio.IncompleteReadError as e:
        raise WireError("connection closed mid-frame") from e
    try:
        header: Any = msgpack.unpackb(raw[:header_len]) if header_len else {}
    except Exception as e:
        # msgpack surfaces corruption as several exception types (its own
        # unpack errors, UnicodeDecodeError for non-utf8 raw strings,
        # ValueError for depth/size) -- all of them are one thing to the
        # conn plane: a malformed frame from a bad peer.
        raise WireError(f"malformed header: {e}") from e
    if not isinstance(header, dict):
        raise WireError("malformed header")
    return Message(t, header, raw[header_len:])

"""Discrete-event swarm simulator: the REAL policy code at 10k-agent scale.

The socket harness tops out at one GIL (~100 MB/s aggregate, PERF.md), so
BASELINE row 6's "p99 pull latency @ 10k agents" cannot be measured with
sockets on this rig. This simulator removes the transport, not the logic:
piece selection (:class:`RequestManager`), conn admission + soft blacklist
(:class:`ConnState`), announce pacing (:class:`AnnounceQueue`) and tracker
handout ordering (:func:`default_priority`) are the production objects,
driven by a simulated clock and an in-memory bandwidth/latency model.
Mirrors the reference's simulated-swarm test tier (SURVEY.md SS4 tier 3,
SS6 row 6) -- upstream testing strategy, unverified.

Model (deliberately simple, stated so results are interpretable):

- Each peer has one uplink of ``uplink_bps``; piece serves queue FIFO on
  it (``busy_until``). ``downlink_bps`` > 0 additionally FIFO-queues the
  receive side at the transfer rate min(uplink, downlink) -- the per-host
  bandwidth-cap shape production ships (utils/bandwidth.py YAML knobs).
  0 keeps the round-3 uplink-only model.
- ``blob_pieces`` with several entries simulates an image-shaped pull:
  every agent pulls ALL blobs concurrently (one conn budget per blob, as
  production conns are per-torrent; one shared uplink/downlink pair per
  host), and an agent's pull latency is when its LAST blob completes --
  what ``docker pull`` wall time means.
- Every message hop pays ``latency_s``.
- Conns are bidirectional, with the dispatcher's idle churn: a conn that
  carries nothing useful for ``churn_idle_s`` is dropped from both ends.
  This is LOAD-BEARING at scale, exactly as the dispatcher's docstring
  claims: without it, completed peers' slots stay pinned to other
  completed peers and a flash crowd wedges (observed in this sim before
  churn was modeled -- 10/200 agents completed, the rest starved).
- Agents announce on join and every ``announce_interval_s`` after
  (complete agents too, as real seeders do); the tracker answers with the
  production handout policy. Announce LOAD is reported, the pacing
  driven through one production :class:`AnnounceQueue`.
- ``restart_frac`` > 0 kills that fraction of agents at ``restart_at_s``:
  conns drop from both ends, in-flight requests are lost, up to
  ``restart_lose_pieces`` most-recent pieces per blob are forgotten (the
  debounced-bitfield crash window), and the agent rejoins after
  ``restart_down_s`` via a fresh announce -- the mid-swarm agent-restart
  chaos shape.
- ``n_trackers`` > 1 models the tracker HA plane (round 12): announces
  shard by info hash over the SAME rendezvous ranking production's
  ``TrackerFleetClient`` uses, each peer carries a real production
  :class:`PassiveFilter` breaker over the tracker hosts (driven with
  sim time), and a failed attempt walks to the next ring tracker.
  Each tracker owns an independent in-memory membership store that DIES
  with it (``tracker_kill_at_s``/``tracker_kill`` kill the blob-0
  owners first; ``tracker_restart_after_s`` revives them empty), so the
  sim measures the real re-form dynamics: failover announces rebuild
  the survivor's swarm view within ~one announce interval. Per-announce
  latency (walk cost included) is reported as ``announce_p50_s`` /
  ``announce_p99_s`` -- the number the tier-1 fleet band pins.
  ``tracker_down_mode`` "refuse" charges one latency hop per dead
  attempt (a killed process RSTs instantly); "blackhole" charges the
  full announce budget (a partitioned host).

Determinism: one seeded ``random.Random`` drives every stochastic choice
(handout shuffle + selection tiebreaks route through ``random`` module
state, seeded per run), so a (seed, config) pair replays exactly.
"""

from __future__ import annotations

import dataclasses
import heapq
import random
import statistics
from typing import Callable, Optional

from kraken_tpu.core.metainfo import InfoHash
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.p2p.announcequeue import AnnounceQueue
from kraken_tpu.p2p.connstate import ConnState, ConnStateConfig
from kraken_tpu.p2p.piecerequest import RequestManager
from kraken_tpu.placement.healthcheck import PassiveFilter
from kraken_tpu.placement.hrw import rendezvous_hash
from kraken_tpu.tracker.peerhandout import default_priority


@dataclasses.dataclass
class SimConfig:
    n_agents: int = 1000
    n_origins: int = 1
    num_pieces: int = 64
    piece_bytes: int = 4 << 20
    uplink_bps: float = 1.25e9  # ~10 GbE
    origin_uplink_bps: float = 1.25e9
    downlink_bps: float = 0.0  # 0 = uplink-only model (round-3 shape)
    latency_s: float = 0.001
    announce_interval_s: float = 3.0
    handout_limit: int = 20
    max_conns_per_torrent: int = 10
    pipeline_limit: int = 4
    piece_timeout_s: float = 8.0
    churn_idle_s: float = 4.0  # dispatcher default
    churn_tick_s: float = 1.0
    seed: int = 0
    max_sim_s: float = 600.0
    # Image-shaped pulls: pieces per blob (None = one blob of num_pieces).
    blob_pieces: tuple[int, ...] | None = None
    # Mid-swarm restart chaos (0 = off).
    restart_at_s: float = 0.0
    restart_frac: float = 0.0
    restart_down_s: float = 1.0
    restart_lose_pieces: int = 1
    # Tracker HA fleet (round 12; 1 = the legacy single-tracker model,
    # bit-for-bit -- the 1k regression band depends on that).
    n_trackers: int = 1
    tracker_kill_at_s: float = 0.0
    tracker_kill: int = 0  # blob-0 owners die first (a miss-less kill)
    tracker_restart_after_s: float = 0.0  # 0 = stays dead
    tracker_down_mode: str = "refuse"  # "refuse" | "blackhole"
    tracker_fail_timeout_s: float = 5.0  # blackhole: announce budget
    tracker_breaker_fails: int = 3
    tracker_breaker_cooldown_s: float = 10.0
    # Gossip peer exchange (p2p/pex.py's model; OFF by default so legacy
    # runs replay bit-exact). Every pex_interval_s each peer offers each
    # conn up to pex_max_peers ids from its known-peer book; receivers
    # merge and dial through the SAME blacklist + capacity gates an
    # announce handout does -- so with every tracker dead the swarm
    # keeps discovering peers over the conns it already has.
    pex: bool = False
    pex_interval_s: float = 5.0
    pex_max_peers: int = 16
    # Total-outage drill: kill EVERY tracker at tracker_kill_at_s
    # (overrides tracker_kill's count).
    tracker_kill_all: bool = False

    def blobs(self) -> tuple[int, ...]:
        return self.blob_pieces or (self.num_pieces,)

    @property
    def fleet(self) -> bool:
        return self.n_trackers > 1


class _Peer:
    """Sim-side agent or origin. Policy objects are the production ones.

    Per-torrent state (``has``/``avail``/``conns``/``requests``) is a
    list indexed by blob; the uplink/downlink queues and the ConnState
    (which natively tracks per-torrent AND global budgets, as production
    does) are per-host."""

    __slots__ = (
        "pid", "origin", "join_t", "done_t", "blob_done_t", "has", "avail",
        "conns", "requests", "cs", "bl", "busy_until", "recv_until",
        "uplink_bps", "offline_until", "order", "incarnation",
        "tracker_health", "known",
    )

    def __init__(self, pid: PeerID, cfg: SimConfig, origin: bool, join_t: float):
        blobs = cfg.blobs()
        self.pid = pid
        self.origin = origin
        self.join_t = join_t
        self.done_t: Optional[float] = None
        self.blob_done_t: list[Optional[float]] = [None] * len(blobs)
        self.has: list[set[int]] = [
            set(range(n)) if origin else set() for n in blobs
        ]
        self.avail: list[dict[int, int]] = [{} for _ in blobs]
        self.conns: list[dict[PeerID, float]] = [{} for _ in blobs]
        self.requests = [
            RequestManager(
                pipeline_limit=cfg.pipeline_limit,
                timeout_seconds=cfg.piece_timeout_s,
            )
            for _ in blobs
        ]
        cs_config = ConnStateConfig(
            max_open_conns_per_torrent=cfg.max_conns_per_torrent,
            # Global cap can't bind with one torrent; keep it out of the way.
            max_global_conns=10 ** 9,
        )
        self.cs = ConnState(cs_config)
        # The blacklist lives OUTSIDE self.cs: ConnState.can_dial consults
        # its own blacklist with wall-clock time, which against sim-time
        # expiries would make results depend on host uptime. This
        # standalone production Blacklist is driven with explicit sim
        # `now`; cs.blacklist stays empty so can_dial's internal check is
        # inert.
        from kraken_tpu.p2p.connstate import Blacklist

        self.bl = Blacklist(cs_config)
        self.busy_until = 0.0
        self.recv_until = 0.0
        self.uplink_bps = cfg.origin_uplink_bps if origin else cfg.uplink_bps
        self.offline_until = 0.0  # restart chaos: no serve/dial while down
        self.order: list[list[int]] = [[] for _ in blobs]  # arrival order
        # Fleet mode: the PRODUCTION breaker over tracker hosts, driven
        # with explicit sim `now` everywhere (like the Blacklist above).
        # One shared instance name keeps the per-filter gauge at a
        # single series however many sim peers exist.
        self.tracker_health: PassiveFilter | None = (
            PassiveFilter(
                fail_threshold=cfg.tracker_breaker_fails,
                cooldown_seconds=cfg.tracker_breaker_cooldown_s,
                name="sim-tracker-fleet",
            )
            if cfg.fleet else None
        )
        # PEX mode: the per-torrent known-peer book (p2p/pex.KnownPeers'
        # role) -- tracker handouts, established conns and received
        # gossip all land here; gossip sends and tracker-free redials
        # draw from it. Restart chaos keeps it: that is the peercache.
        self.known: list[set[PeerID]] = [set() for _ in blobs]
        # Bumped on every restart: events scheduled against the OLD
        # process (queued serves, in-flight pieces) must not charge or
        # feed the reborn one.
        self.incarnation = 0

    def offline(self, now: float) -> bool:
        return now < self.offline_until

    def complete(self) -> bool:
        return self.done_t is not None or self.origin

    def blob_complete(self, t: int) -> bool:
        return self.origin or self.blob_done_t[t] is not None


class _SimTracker:
    """One fleet tracker: an up/down flag and an independent in-memory
    membership store (per torrent) that dies with the process."""

    __slots__ = ("name", "up", "members", "member_set")

    def __init__(self, name: str, n_blobs: int):
        self.name = name
        self.up = True
        self.members: list[list[PeerID]] = [[] for _ in range(n_blobs)]
        self.member_set: list[set[PeerID]] = [set() for _ in range(n_blobs)]

    def wipe(self) -> None:
        for t in range(len(self.members)):
            self.members[t].clear()
            self.member_set[t].clear()


class SwarmSim:
    """``n_agents`` leechers x ``blobs()`` torrents, ``n_origins`` seeders."""

    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.blobs = cfg.blobs()
        self.hs = [
            InfoHash(f"{t:02x}" + "ab" * 31) for t in range(len(self.blobs))
        ]
        self.now = 0.0
        self._heap: list[tuple[float, int, Callable[[], None]]] = []
        self._seq = 0
        self.peers: dict[PeerID, _Peer] = {}
        self.announce_q = AnnounceQueue()
        self.announces = 0
        self.transfers = 0
        self.duplicates = 0
        self.busy_rejects = 0
        self.restarts = 0
        self._remaining = cfg.n_agents  # agents with >= 1 incomplete blob
        # Tracker swarm membership per torrent (each pid once, append-only:
        # the sim has no TTL churn). Handouts SAMPLE this, as the
        # production peerstore does; completeness is read from live peer
        # state, a one-interval-fresher view than the tracker's records.
        self._members: list[list[PeerID]] = [[] for _ in self.blobs]
        self._member_set: list[set[PeerID]] = [set() for _ in self.blobs]
        # Fleet mode state (cfg.n_trackers > 1; legacy single-tracker
        # runs never touch any of it, preserving bit-exact replays).
        self.trackers: list[_SimTracker] = [
            _SimTracker(f"tracker{i}", len(self.blobs))
            for i in range(cfg.n_trackers)
        ] if cfg.fleet else []
        self._tracker_by_name = {tr.name: tr for tr in self.trackers}
        self.announce_lat: list[float] = []
        self.announce_failovers = 0  # attempts that walked past a tracker
        self.announce_failures = 0   # walks that exhausted the whole fleet
        self.tracker_kills = 0
        self.pex_messages = 0  # gossip frames sent
        self.pex_dials = 0     # dials sourced from gossip/book, not announces

    # -- event plumbing ----------------------------------------------------

    def _at(self, t: float, fn: Callable[[], None]) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, fn))

    def run(self) -> dict:
        random.seed(self.cfg.seed)
        cfg = self.cfg
        for i in range(cfg.n_origins):
            pid = PeerID("ff" * 2 + f"{i:036x}")
            self.peers[pid] = _Peer(pid, cfg, origin=True, join_t=0.0)
            for t in range(len(self.blobs)):
                if cfg.fleet:
                    # The origin registers with each swarm's shard OWNER
                    # (where its production seed-announce would land) and
                    # keeps re-announcing via the queue -- that periodic
                    # announce is what re-registers it with the failover
                    # tracker after the owner dies.
                    tr = self._tracker_by_name[self._owner(t)]
                    tr.members[t].append(pid)
                    tr.member_set[t].add(pid)
                    self.announce_q.schedule(
                        (pid, t), cfg.announce_interval_s
                    )
                else:
                    self._members[t].append(pid)
                    self._member_set[t].add(pid)
        for i in range(cfg.n_agents):
            pid = PeerID(f"{i:040x}")
            self.peers[pid] = _Peer(pid, cfg, origin=False, join_t=0.0)
            for t in range(len(self.blobs)):
                self.announce_q.schedule((pid, t), 0.0)
        # One announce pump, as in the production scheduler: drain due
        # announces in batches rather than a timer per peer.
        self._at(0.0, self._announce_pump)
        self._at(cfg.churn_tick_s, self._churn_tick)
        if cfg.restart_frac > 0 and cfg.restart_at_s > 0:
            self._at(cfg.restart_at_s, self._restart_wave)
        if cfg.fleet and cfg.tracker_kill_at_s > 0 and (
            cfg.tracker_kill > 0 or cfg.tracker_kill_all
        ):
            self._at(cfg.tracker_kill_at_s, self._tracker_kill_wave)
        if cfg.pex:
            self._at(cfg.pex_interval_s, self._pex_tick)

        while self._heap and self.now <= cfg.max_sim_s and self._remaining:
            t, _seq, fn = heapq.heappop(self._heap)
            self.now = t
            fn()
        return self._report()

    # -- announce plane ----------------------------------------------------

    def _announce_pump(self) -> None:
        for key in self.announce_q.pop_ready(self.now, limit=10 ** 6):
            pid, t = key
            p = self.peers[pid]
            if p.offline(self.now):
                # Down agents re-announce when they come back.
                self.announce_q.schedule(key, p.offline_until)
                continue
            self._announce(p, t)
        if self._remaining:
            self._at(self.now + 0.05, self._announce_pump)

    def _info(self, pid: PeerID, t: int) -> PeerInfo:
        p = self.peers[pid]
        return PeerInfo(
            pid, "sim", 0, origin=p.origin, complete=p.blob_complete(t)
        )

    def _announce(self, p: _Peer, t: int) -> None:
        if self.cfg.fleet:
            self._announce_fleet(p, t)
            return
        self.announces += 1
        # Tracker side: record membership, sample candidates (as the
        # production peerstore does), order with the production policy.
        if p.pid not in self._member_set[t]:
            self._member_set[t].add(p.pid)
            self._members[t].append(p.pid)
        limit = self.cfg.handout_limit
        k = min(len(self._members[t]), limit + 1)
        candidates = random.sample(self._members[t], k)
        others = [self._info(q, t) for q in candidates if q != p.pid][:limit]
        handout = default_priority(others)
        if self.cfg.pex:
            for info in handout:
                p.known[t].add(info.peer_id)
        self.announce_q.schedule(
            (p.pid, t), self.now + self.cfg.announce_interval_s
        )
        if p.blob_complete(t):
            return  # seeders announce for discoverability, don't dial
        for info in handout:
            self._try_dial(p, info.peer_id, t)

    # -- tracker fleet (round 12) ------------------------------------------

    def _owner(self, t: int) -> str:
        return rendezvous_hash(
            self.hs[t].hex, [tr.name for tr in self.trackers], k=1
        )[0]

    def _announce_fleet(self, p: _Peer, t: int) -> None:
        """One announce through the fleet: rendezvous ranking (owner
        first), production-breaker ordering and probe admission, walk on
        failure -- the TrackerFleetClient policy in sim time. The walk's
        accumulated cost IS the announce latency the band test pins."""
        self.announces += 1
        names = [tr.name for tr in self.trackers]
        ranked = rendezvous_hash(self.hs[t].hex, names, k=len(names))
        health = p.tracker_health
        chosen: _SimTracker | None = None
        delay = 0.0
        for admit in (True, False):
            attempted = False
            for name in health.order(ranked, now=self.now):
                if admit:
                    if not health.try_acquire_probe(name, now=self.now + delay):
                        continue  # open-and-cooling, or probe taken
                attempted = True
                tr = self._tracker_by_name[name]
                if not tr.up:
                    # "refuse": a killed process RSTs instantly -- one
                    # hop to learn. "blackhole": the attempt burns the
                    # announce budget before the walk moves on.
                    delay += (
                        self.cfg.latency_s
                        if self.cfg.tracker_down_mode == "refuse"
                        else self.cfg.tracker_fail_timeout_s
                    )
                    self.announce_failovers += 1
                    health.observe(name, False, now=self.now + delay)
                    continue
                rtt = 2 * self.cfg.latency_s
                delay += rtt
                health.observe(name, True, rtt, now=self.now + delay)
                chosen = tr
                break
            if chosen is not None or attempted:
                break
            # Every tracker was skipped by the probe gate: walk again
            # all-in (serving badly beats serving nothing -- the same
            # degrade the production walk takes).
        self.announce_lat.append(delay)
        self.announce_q.schedule(
            (p.pid, t), self.now + delay + self.cfg.announce_interval_s
        )
        if chosen is None:
            self.announce_failures += 1  # whole fleet down: retry next tick
            return
        self._at(self.now + delay,
                 lambda: self._announce_apply(p, t, chosen))

    def _announce_apply(self, p: _Peer, t: int, tr: _SimTracker) -> None:
        """The announce lands at a live tracker: register membership in
        ITS store (re-forming the swarm there after a failover), sample
        a handout from what IT knows, dial."""
        if not tr.up or p.offline(self.now):
            return  # the tracker (or the announcer) died in flight
        if p.pid not in tr.member_set[t]:
            tr.member_set[t].add(p.pid)
            tr.members[t].append(p.pid)
        limit = self.cfg.handout_limit
        k = min(len(tr.members[t]), limit + 1)
        candidates = random.sample(tr.members[t], k)
        others = [self._info(q, t) for q in candidates if q != p.pid][:limit]
        handout = default_priority(others)
        if self.cfg.pex:
            for info in handout:
                p.known[t].add(info.peer_id)
        if p.blob_complete(t):
            return  # seeders announce for discoverability, don't dial
        for info in handout:
            self._try_dial(p, info.peer_id, t)

    def _tracker_kill_wave(self) -> None:
        """Kill the blob-0 shard owners first (a random victim could
        miss the shard under test entirely), wiping their in-memory
        stores -- exactly what a process death does. Optional revival
        brings them back EMPTY; announces re-form the swarm."""
        names = [tr.name for tr in self.trackers]
        ranked = rendezvous_hash(self.hs[0].hex, names, k=len(names))
        kill = (
            len(ranked) if self.cfg.tracker_kill_all
            else self.cfg.tracker_kill
        )
        for name in ranked[:kill]:
            tr = self._tracker_by_name[name]
            tr.up = False
            tr.wipe()
            self.tracker_kills += 1
            if self.cfg.tracker_restart_after_s > 0:
                self._at(
                    self.now + self.cfg.tracker_restart_after_s,
                    lambda tr=tr: setattr(tr, "up", True),
                )

    # -- conn plane --------------------------------------------------------

    def _try_dial(self, a: _Peer, bid: PeerID, t: int) -> None:
        # Sim-time blacklist check against the peer's standalone
        # Blacklist (see _Peer.bl for why it is not cs.blacklist).
        if a.bl.blocked(bid, self.hs[t], now=self.now):
            return
        if not a.cs.add_pending(bid, self.hs[t]):
            return
        self._at(self.now + self.cfg.latency_s,
                 lambda: self._dial_arrives(a, bid, t))

    def _dial_arrives(self, a: _Peer, bid: PeerID, t: int) -> None:
        b = self.peers[bid]
        if b.offline(self.now) or b.cs.at_capacity(self.hs[t]):
            # Polite busy frame -> soft blacklist, as the production
            # scheduler does on a busy rejection (scheduler.py:412). A
            # down host answers nothing; connection refused takes the
            # same soft-blacklist path in production.
            self.busy_rejects += 1
            self._at(self.now + self.cfg.latency_s, lambda: (
                a.cs.remove_pending(bid, self.hs[t]),
                a.bl.add(bid, self.hs[t], now=self.now, soft=True),
            ))
            return
        b.cs.promote(a.pid, self.hs[t])  # inbound: promote directly
        self._at(self.now + self.cfg.latency_s,
                 lambda: self._established(a, b, t))

    def _established(self, a: _Peer, b: _Peer, t: int) -> None:
        a.cs.promote(b.pid, self.hs[t])
        if self.cfg.pex:
            # A live conn IS peer knowledge ("conn"-sourced book entry).
            a.known[t].add(b.pid)
            b.known[t].add(a.pid)
        for x, y in ((a, b), (b, a)):
            if y.pid not in x.conns[t]:
                x.conns[t][y.pid] = self.now
                for i in y.has[t]:
                    x.avail[t][i] = x.avail[t].get(i, 0) + 1
        self._select(a, b, t)
        self._select(b, a, t)

    def _drop_conn(self, x: _Peer, y: _Peer, t: int) -> None:
        if y.pid not in x.conns[t]:
            return
        for a, b in ((x, y), (y, x)):
            del a.conns[t][b.pid]
            a.cs.remove(b.pid, self.hs[t])
            a.requests[t].clear_peer(b.pid)
            # Clamped decrement: an announce in flight when the conn drops
            # was never counted, so subtracting b's full has-set can
            # transiently undercount by one -- bounded by the latency
            # window, and preferable to per-conn piece snapshots (O(conns
            # x pieces) memory at 10k agents).
            for i in b.has[t]:
                n = a.avail[t].get(i, 0) - 1
                if n > 0:
                    a.avail[t][i] = n
                else:
                    a.avail[t].pop(i, None)

    def _churn_tick(self) -> None:
        cutoff = self.cfg.churn_idle_s
        for p in self.peers.values():
            for t in range(len(self.blobs)):
                for qid, last in list(p.conns[t].items()):
                    if self.now - last > cutoff:
                        self._drop_conn(p, self.peers[qid], t)
        if self._remaining:
            self._at(self.now + self.cfg.churn_tick_s, self._churn_tick)

    # -- gossip peer exchange ----------------------------------------------

    def _pex_tick(self) -> None:
        """One gossip round, modeling p2p/pex.py: every online peer
        offers each conn up to ``pex_max_peers`` ids from its known book
        (live conns included -- production's ``delta_for`` snapshots the
        live book). Gossip is NOT useful traffic (no churn exemption, as
        the dispatcher rules), and every dial -- on receive AND from the
        retry-loop redial below -- goes through the SAME blacklist +
        capacity gates an announce handout does."""
        cfg = self.cfg
        for p in self.peers.values():
            if p.offline(self.now):
                continue
            for t in range(len(self.blobs)):
                pool = p.known[t] | set(p.conns[t])
                pool.discard(p.pid)
                if not pool:
                    continue
                ordered = sorted(pool)
                for qid in list(p.conns[t]):
                    cand = [x for x in ordered if x != qid]
                    if len(cand) > cfg.pex_max_peers:
                        cand = random.sample(cand, cfg.pex_max_peers)
                    if not cand:
                        continue
                    self.pex_messages += 1
                    q = self.peers[qid]
                    self._at(
                        self.now + cfg.latency_s,
                        lambda q=q, t=t, cand=cand:
                            self._pex_receive(q, t, cand),
                    )
                # The scheduler's retry loop over the book: an
                # incomplete agent redials known peers it is not
                # connected to -- this is what un-strands an agent
                # whose every conn churned away while the trackers are
                # dead (its book is the only discovery plane left).
                if not p.origin and not p.blob_complete(t):
                    for pid in ordered:
                        if pid not in p.conns[t]:
                            self.pex_dials += 1
                            self._try_dial(p, pid, t)
        if self._remaining:
            self._at(self.now + cfg.pex_interval_s, self._pex_tick)

    def _pex_receive(self, q: _Peer, t: int, cand: list[PeerID]) -> None:
        if q.offline(self.now):
            return
        for pid in cand:
            if pid != q.pid and pid in self.peers:
                q.known[t].add(pid)
        if q.origin or q.blob_complete(t):
            return
        for pid in cand:
            if pid != q.pid and pid in self.peers and pid not in q.conns[t]:
                self.pex_dials += 1
                self._try_dial(q, pid, t)

    # -- restart chaos -----------------------------------------------------

    def _restart_wave(self) -> None:
        cfg = self.cfg
        agents = [
            p for p in self.peers.values()
            if not p.origin and not p.offline(self.now)
        ]
        victims = random.sample(
            agents, int(len(agents) * cfg.restart_frac)
        )
        for p in victims:
            self.restarts += 1
            was_complete = p.done_t is not None
            p.offline_until = self.now + cfg.restart_down_s
            p.incarnation += 1
            # The reborn process has EMPTY transfer queues: bytes queued
            # toward (or from) the dead one were never delivered and must
            # not phantom-saturate either bucket after rejoin.
            p.recv_until = 0.0
            p.busy_until = 0.0
            for t in range(len(self.blobs)):
                for qid in list(p.conns[t]):
                    self._drop_conn(p, self.peers[qid], t)
                # The debounced-bitfield crash window: the most recent
                # pieces may not have hit the sidecar. (Guarded: a -0
                # slice would mean "lose everything", not "lose none".)
                lost = (
                    p.order[t][-cfg.restart_lose_pieces:]
                    if cfg.restart_lose_pieces > 0 else []
                )
                for i in reversed(lost):
                    if i in p.has[t]:
                        p.has[t].discard(i)
                        p.order[t].remove(i)
                        if p.blob_done_t[t] is not None:
                            p.blob_done_t[t] = None
                # In-flight requests died with the process.
                p.requests[t] = RequestManager(
                    pipeline_limit=cfg.pipeline_limit,
                    timeout_seconds=cfg.piece_timeout_s,
                )
                self.announce_q.schedule((p.pid, t), p.offline_until)
            if was_complete and any(
                p.blob_done_t[t] is None for t in range(len(self.blobs))
            ):
                p.done_t = None
                self._remaining += 1

    # -- piece plane -------------------------------------------------------

    def _select(self, a: _Peer, b: _Peer, t: int) -> None:
        """``a`` asks the production RequestManager what to fetch from
        ``b`` and schedules the transfers."""
        if (
            a.origin or a.blob_done_t[t] is not None
            or b.pid not in a.conns[t] or a.offline(self.now)
        ):
            return
        missing = [i for i in range(self.blobs[t]) if i not in a.has[t]]
        if not missing:
            return
        chosen = a.requests[t].select(
            b.pid, b.has[t], missing, a.avail[t], now=self.now
        )
        for i in chosen:
            self._at(self.now + self.cfg.latency_s,
                     lambda i=i: self._serve(b, a, i, t))

    def _serve(self, b: _Peer, a: _Peer, i: int, t: int) -> None:
        """Request for piece ``i`` arrives at ``b``: FIFO-queue it on b's
        uplink (and a's downlink when caps are modeled)."""
        if i not in b.has[t] or b.offline(self.now) or a.offline(self.now):
            return  # raced ahead of an announce / host down; timeout re-requests
        if a.pid in b.conns[t]:
            b.conns[t][a.pid] = self.now  # a request is useful traffic
        # Sender and receiver each FIFO on their OWN bucket; completion is
        # when both have passed the bytes. Holding the sender's queue for
        # a slow receiver's duration instead (the first model tried)
        # head-of-line-blocks every other download behind one capped
        # receiver -- a wedge real multiplexed TCP does not have (a 10k
        # capped run completed 0 agents in 600 sim-seconds under it).
        up_start = max(self.now, b.busy_until)
        up_done = up_start + self.cfg.piece_bytes / b.uplink_bps
        b.busy_until = up_done
        done = up_done
        if self.cfg.downlink_bps > 0:
            dn_start = max(up_start, a.recv_until)
            dn_done = dn_start + self.cfg.piece_bytes / self.cfg.downlink_bps
            a.recv_until = dn_done
            done = max(done, dn_done)
        inc = a.incarnation
        sinc = b.incarnation
        self._at(done + self.cfg.latency_s,
                 lambda: self._on_piece(a, b, i, t, inc, sinc))

    def _on_piece(
        self, a: _Peer, b: _Peer, i: int, t: int, inc: int, sinc: int
    ) -> None:
        if a.offline(self.now) or inc != a.incarnation:
            return  # arrived at a dead (or since-restarted) process
        if sinc != b.incarnation:
            return  # the SENDER died mid-serve: its socket died with it
        self.transfers += 1
        if b.pid in a.conns[t]:
            a.conns[t][b.pid] = self.now  # payload is useful traffic
        a.requests[t].clear_piece(i, now=self.now)
        if i in a.has[t] or a.blob_done_t[t] is not None:
            self.duplicates += 1
            self._select(a, b, t)  # endgame duplicate: just keep pulling
            return
        a.has[t].add(i)
        a.order[t].append(i)
        # Announce the new piece to every conn (metadata hop).
        for cid in a.conns[t]:
            c = self.peers[cid]
            self._at(self.now + self.cfg.latency_s,
                     lambda a=a, c=c, i=i: self._on_announce_piece(c, a, i, t))
        if len(a.has[t]) == self.blobs[t]:
            a.blob_done_t[t] = self.now
            if all(d is not None for d in a.blob_done_t):
                a.done_t = self.now
                self._remaining -= 1
            return
        self._select(a, b, t)

    def _on_announce_piece(self, c: _Peer, a: _Peer, i: int, t: int) -> None:
        if a.pid not in c.conns[t]:
            return
        c.conns[t][a.pid] = self.now  # progress announce is useful traffic
        c.avail[t][i] = c.avail[t].get(i, 0) + 1
        self._select(c, a, t)

    # -- reporting ---------------------------------------------------------

    def _report(self) -> dict:
        lat = sorted(
            p.done_t - p.join_t
            for p in self.peers.values()
            if not p.origin and p.done_t is not None
        )
        n = len(lat)
        incomplete = self.cfg.n_agents - n
        q = (lambda f: lat[min(n - 1, int(f * n))]) if n else (lambda f: None)
        alat = sorted(self.announce_lat)
        na = len(alat)
        aq = (
            (lambda f: alat[min(na - 1, int(f * na))]) if na
            else (lambda f: None)
        )
        return {
            "agents": self.cfg.n_agents,
            "blobs": len(self.blobs),
            "completed": n,
            "incomplete": incomplete,
            "p50_s": q(0.50),
            "p99_s": q(0.99),
            "max_s": lat[-1] if n else None,
            "mean_s": statistics.fmean(lat) if n else None,
            "sim_end_s": self.now,
            "announces": self.announces,
            "announces_per_s": self.announces / self.now if self.now else 0.0,
            "transfers": self.transfers,
            "duplicate_transfers": self.duplicates,
            "busy_rejects": self.busy_rejects,
            "restarts": self.restarts,
            # Tracker fleet plane (None/0 outside fleet mode: legacy
            # announces are instantaneous in-model).
            "n_trackers": self.cfg.n_trackers,
            "announce_p50_s": aq(0.50),
            "announce_p99_s": aq(0.99),
            "announce_failovers": self.announce_failovers,
            "announce_failures": self.announce_failures,
            "tracker_kills": self.tracker_kills,
            # Gossip plane (0 outside pex mode).
            "pex_messages": self.pex_messages,
            "pex_dials": self.pex_dials,
        }


def run_sim(**overrides) -> dict:
    return SwarmSim(SimConfig(**overrides)).run()

"""Discrete-event swarm simulator: the REAL policy code at 10k-agent scale.

The socket harness tops out at one GIL (~100 MB/s aggregate, PERF.md), so
BASELINE row 6's "p99 pull latency @ 10k agents" cannot be measured with
sockets on this rig. This simulator removes the transport, not the logic:
piece selection (:class:`RequestManager`), conn admission + soft blacklist
(:class:`ConnState`), announce pacing (:class:`AnnounceQueue`) and tracker
handout ordering (:func:`default_priority`) are the production objects,
driven by a simulated clock and an in-memory bandwidth/latency model.
Mirrors the reference's simulated-swarm test tier (SURVEY.md SS4 tier 3,
SS6 row 6) -- upstream testing strategy, unverified.

Model (deliberately simple, stated so results are interpretable):

- Each peer has one uplink of ``uplink_bps``; piece serves queue FIFO on
  it (``busy_until``). Downlinks are not modeled separately -- swarm
  goodput is uplink-bound, and modeling both would double event count for
  a second-order effect.
- Every message hop pays ``latency_s``.
- Conns are bidirectional, with the dispatcher's idle churn: a conn that
  carries nothing useful for ``churn_idle_s`` is dropped from both ends.
  This is LOAD-BEARING at scale, exactly as the dispatcher's docstring
  claims: without it, completed peers' slots stay pinned to other
  completed peers and a flash crowd wedges (observed in this sim before
  churn was modeled -- 10/200 agents completed, the rest starved).
- Agents announce on join and every ``announce_interval_s`` after
  (complete agents too, as real seeders do); the tracker answers with the
  production handout policy. Announce LOAD is reported, the pacing
  driven through one production :class:`AnnounceQueue`.

Determinism: one seeded ``random.Random`` drives every stochastic choice
(handout shuffle + selection tiebreaks route through ``random`` module
state, seeded per run), so a (seed, config) pair replays exactly.
"""

from __future__ import annotations

import dataclasses
import heapq
import random
import statistics
from typing import Callable, Optional

from kraken_tpu.core.metainfo import InfoHash
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.p2p.announcequeue import AnnounceQueue
from kraken_tpu.p2p.connstate import ConnState, ConnStateConfig
from kraken_tpu.p2p.piecerequest import RequestManager
from kraken_tpu.tracker.peerhandout import default_priority


@dataclasses.dataclass
class SimConfig:
    n_agents: int = 1000
    n_origins: int = 1
    num_pieces: int = 64
    piece_bytes: int = 4 << 20
    uplink_bps: float = 1.25e9  # ~10 GbE
    origin_uplink_bps: float = 1.25e9
    latency_s: float = 0.001
    announce_interval_s: float = 3.0
    handout_limit: int = 20
    max_conns_per_torrent: int = 10
    pipeline_limit: int = 4
    piece_timeout_s: float = 8.0
    churn_idle_s: float = 4.0  # dispatcher default
    churn_tick_s: float = 1.0
    seed: int = 0
    max_sim_s: float = 600.0


class _Peer:
    """Sim-side agent or origin. Policy objects are the production ones."""

    __slots__ = (
        "pid", "origin", "join_t", "done_t", "has", "avail", "conns",
        "requests", "cs", "bl", "busy_until", "uplink_bps",
    )

    def __init__(self, pid: PeerID, cfg: SimConfig, origin: bool, join_t: float):
        self.pid = pid
        self.origin = origin
        self.join_t = join_t
        self.done_t: Optional[float] = None
        self.has: set[int] = set(range(cfg.num_pieces)) if origin else set()
        self.avail: dict[int, int] = {}  # piece -> count over conns
        self.conns: dict[PeerID, float] = {}  # peer -> last_useful
        self.requests = RequestManager(
            pipeline_limit=cfg.pipeline_limit,
            timeout_seconds=cfg.piece_timeout_s,
        )
        cs_config = ConnStateConfig(
            max_open_conns_per_torrent=cfg.max_conns_per_torrent,
            # Global cap can't bind with one torrent; keep it out of the way.
            max_global_conns=10 ** 9,
        )
        self.cs = ConnState(cs_config)
        # The blacklist lives OUTSIDE self.cs: ConnState.can_dial consults
        # its own blacklist with wall-clock time, which against sim-time
        # expiries would make results depend on host uptime. This
        # standalone production Blacklist is driven with explicit sim
        # `now`; cs.blacklist stays empty so can_dial's internal check is
        # inert.
        from kraken_tpu.p2p.connstate import Blacklist

        self.bl = Blacklist(cs_config)
        self.busy_until = 0.0
        self.uplink_bps = cfg.origin_uplink_bps if origin else cfg.uplink_bps

    def complete(self) -> bool:
        return self.done_t is not None or self.origin


class SwarmSim:
    """One blob, ``n_agents`` leechers, ``n_origins`` seeders."""

    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.h = InfoHash("ab" * 32)
        self.now = 0.0
        self._heap: list[tuple[float, int, Callable[[], None]]] = []
        self._seq = 0
        self.peers: dict[PeerID, _Peer] = {}
        self.announce_q = AnnounceQueue()
        self.announces = 0
        self.transfers = 0
        self.duplicates = 0
        self.busy_rejects = 0
        self._remaining = cfg.n_agents  # incomplete agents
        # Tracker swarm membership (each pid once, append-only: the sim
        # has no TTL churn). Handouts SAMPLE this, as the production
        # peerstore does; completeness is read from live peer state, a
        # one-interval-fresher view than the tracker's announce records.
        self._members: list[PeerID] = []
        self._member_set: set[PeerID] = set()

    # -- event plumbing ----------------------------------------------------

    def _at(self, t: float, fn: Callable[[], None]) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, fn))

    def run(self) -> dict:
        random.seed(self.cfg.seed)
        cfg = self.cfg
        for i in range(cfg.n_origins):
            pid = PeerID("ff" * 2 + f"{i:036x}")
            self.peers[pid] = _Peer(pid, cfg, origin=True, join_t=0.0)
            self._members.append(pid)
            self._member_set.add(pid)
        for i in range(cfg.n_agents):
            pid = PeerID(f"{i:040x}")
            self.peers[pid] = _Peer(pid, cfg, origin=False, join_t=0.0)
            self.announce_q.schedule(pid, 0.0)
        # One announce pump, as in the production scheduler: drain due
        # announces in batches rather than a timer per peer.
        self._at(0.0, self._announce_pump)
        self._at(cfg.churn_tick_s, self._churn_tick)

        while self._heap and self.now <= cfg.max_sim_s and self._remaining:
            t, _seq, fn = heapq.heappop(self._heap)
            self.now = t
            fn()
        return self._report()

    # -- announce plane ----------------------------------------------------

    def _announce_pump(self) -> None:
        for pid in self.announce_q.pop_ready(self.now, limit=10 ** 6):
            self._announce(self.peers[pid])
        if self._remaining:
            self._at(self.now + 0.05, self._announce_pump)

    def _info(self, pid: PeerID) -> PeerInfo:
        p = self.peers[pid]
        return PeerInfo(pid, "sim", 0, origin=p.origin, complete=p.complete())

    def _announce(self, p: _Peer) -> None:
        self.announces += 1
        # Tracker side: record membership, sample candidates (as the
        # production peerstore does), order with the production policy.
        if p.pid not in self._member_set:
            self._member_set.add(p.pid)
            self._members.append(p.pid)
        limit = self.cfg.handout_limit
        k = min(len(self._members), limit + 1)
        candidates = random.sample(self._members, k)
        others = [self._info(q) for q in candidates if q != p.pid][:limit]
        handout = default_priority(others)
        self.announce_q.schedule(
            p.pid, self.now + self.cfg.announce_interval_s
        )
        if p.complete():
            return  # seeders announce for discoverability, don't dial
        for info in handout:
            self._try_dial(p, info.peer_id)

    # -- conn plane --------------------------------------------------------

    def _try_dial(self, a: _Peer, bid: PeerID) -> None:
        # Sim-time blacklist check against the peer's standalone
        # Blacklist (see _Peer.bl for why it is not cs.blacklist).
        if a.bl.blocked(bid, self.h, now=self.now):
            return
        if not a.cs.add_pending(bid, self.h):
            return
        self._at(self.now + self.cfg.latency_s,
                 lambda: self._dial_arrives(a, bid))

    def _dial_arrives(self, a: _Peer, bid: PeerID) -> None:
        b = self.peers[bid]
        if b.cs.at_capacity(self.h):
            # Polite busy frame -> soft blacklist, as the production
            # scheduler does on a busy rejection (scheduler.py:412).
            self.busy_rejects += 1
            self._at(self.now + self.cfg.latency_s, lambda: (
                a.cs.remove_pending(bid, self.h),
                a.bl.add(bid, self.h, now=self.now, soft=True),
            ))
            return
        b.cs.promote(a.pid, self.h)  # inbound: promote directly
        self._at(self.now + self.cfg.latency_s,
                 lambda: self._established(a, b))

    def _established(self, a: _Peer, b: _Peer) -> None:
        a.cs.promote(b.pid, self.h)
        for x, y in ((a, b), (b, a)):
            if y.pid not in x.conns:
                x.conns[y.pid] = self.now
                for i in y.has:
                    x.avail[i] = x.avail.get(i, 0) + 1
        self._select(a, b)
        self._select(b, a)

    def _drop_conn(self, x: _Peer, y: _Peer) -> None:
        if y.pid not in x.conns:
            return
        for a, b in ((x, y), (y, x)):
            del a.conns[b.pid]
            a.cs.remove(b.pid, self.h)
            a.requests.clear_peer(b.pid)
            # Clamped decrement: an announce in flight when the conn drops
            # was never counted, so subtracting b's full has-set can
            # transiently undercount by one -- bounded by the latency
            # window, and preferable to per-conn piece snapshots (O(conns
            # x pieces) memory at 10k agents).
            for i in b.has:
                n = a.avail.get(i, 0) - 1
                if n > 0:
                    a.avail[i] = n
                else:
                    a.avail.pop(i, None)

    def _churn_tick(self) -> None:
        cutoff = self.cfg.churn_idle_s
        for p in self.peers.values():
            for qid, last in list(p.conns.items()):
                if self.now - last > cutoff:
                    self._drop_conn(p, self.peers[qid])
        if self._remaining:
            self._at(self.now + self.cfg.churn_tick_s, self._churn_tick)

    # -- piece plane -------------------------------------------------------

    def _select(self, a: _Peer, b: _Peer) -> None:
        """``a`` asks the production RequestManager what to fetch from
        ``b`` and schedules the transfers."""
        if a.origin or a.done_t is not None or b.pid not in a.conns:
            return
        missing = [i for i in range(self.cfg.num_pieces) if i not in a.has]
        if not missing:
            return
        chosen = a.requests.select(
            b.pid, b.has, missing, a.avail, now=self.now
        )
        for i in chosen:
            self._at(self.now + self.cfg.latency_s,
                     lambda i=i: self._serve(b, a, i))

    def _serve(self, b: _Peer, a: _Peer, i: int) -> None:
        """Request for piece ``i`` arrives at ``b``: FIFO-queue it on b's
        uplink."""
        if i not in b.has:
            return  # raced ahead of an announce; timeout will re-request
        if a.pid in b.conns:
            b.conns[a.pid] = self.now  # a request is useful traffic
        start = max(self.now, b.busy_until)
        done = start + self.cfg.piece_bytes / b.uplink_bps
        b.busy_until = done
        self._at(done + self.cfg.latency_s,
                 lambda: self._on_piece(a, b, i))

    def _on_piece(self, a: _Peer, b: _Peer, i: int) -> None:
        self.transfers += 1
        if b.pid in a.conns:
            a.conns[b.pid] = self.now  # payload is useful traffic
        a.requests.clear_piece(i, now=self.now)
        if i in a.has or a.done_t is not None:
            self.duplicates += 1
            self._select(a, b)  # endgame duplicate: just keep pulling
            return
        a.has.add(i)
        # Announce the new piece to every conn (metadata hop).
        for cid in a.conns:
            c = self.peers[cid]
            self._at(self.now + self.cfg.latency_s,
                     lambda a=a, c=c, i=i: self._on_announce_piece(c, a, i))
        if len(a.has) == self.cfg.num_pieces:
            a.done_t = self.now
            self._remaining -= 1
            return
        self._select(a, b)

    def _on_announce_piece(self, c: _Peer, a: _Peer, i: int) -> None:
        if a.pid not in c.conns:
            return
        c.conns[a.pid] = self.now  # progress announce is useful traffic
        c.avail[i] = c.avail.get(i, 0) + 1
        self._select(c, a)

    # -- reporting ---------------------------------------------------------

    def _report(self) -> dict:
        lat = sorted(
            p.done_t - p.join_t
            for p in self.peers.values()
            if not p.origin and p.done_t is not None
        )
        n = len(lat)
        incomplete = self.cfg.n_agents - n
        q = (lambda f: lat[min(n - 1, int(f * n))]) if n else (lambda f: None)
        return {
            "agents": self.cfg.n_agents,
            "completed": n,
            "incomplete": incomplete,
            "p50_s": q(0.50),
            "p99_s": q(0.99),
            "max_s": lat[-1] if n else None,
            "mean_s": statistics.fmean(lat) if n else None,
            "sim_end_s": self.now,
            "announces": self.announces,
            "announces_per_s": self.announces / self.now if self.now else 0.0,
            "transfers": self.transfers,
            "duplicate_transfers": self.duplicates,
            "busy_rejects": self.busy_rejects,
        }


def run_sim(**overrides) -> dict:
    return SwarmSim(SimConfig(**overrides)).run()

"""Per-torrent dispatcher: drives piece exchange over a set of peer conns.

Mirrors uber/kraken ``lib/torrent/scheduler/dispatch`` (tracks which peer
has which pieces, piece request lifecycle, writes received pieces to
storage, re-announces completed pieces to connected peers, endgame &
failure handling) -- upstream path, unverified; SURVEY.md SS2.2.

One Dispatcher per torrent. Each added conn gets a recv-pump task; all
state mutation happens on the scheduler's event loop (asyncio's
single-thread invariant mirrors the reference's single-goroutine design).
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Callable, Optional

from kraken_tpu.core.peer import PeerID
from kraken_tpu.p2p.conn import Conn, ConnClosedError
from kraken_tpu.p2p.networkevent import NoopProducer, Producer
from kraken_tpu.p2p.piecerequest import RequestManager
from kraken_tpu.p2p.storage import PieceError, Torrent
from kraken_tpu.p2p.wire import Message, MsgType
from kraken_tpu.utils import trace
from kraken_tpu.utils.metrics import REGISTRY


def _bits_to_set(bits: bytes, num_pieces: int) -> set[int]:
    """Decode a peer bitfield, validating its length (a short bitfield from
    a hostile or version-skewed peer must not crash the adopter)."""
    if len(bits) < (num_pieces + 7) // 8:
        raise PieceError(
            f"bitfield too short: {len(bits)} bytes for {num_pieces} pieces"
        )
    return {i for i in range(num_pieces) if bits[i // 8] >> (i % 8) & 1}


class _Peer:
    __slots__ = (
        "conn", "has", "pump", "complete", "last_useful", "serving",
        "receiving",
    )

    def __init__(self, conn: Conn, has: set[int], now: float):
        self.conn = conn
        self.has = has
        self.pump: Optional[asyncio.Task] = None
        self.complete = False
        # Last time this conn carried anything of value (payload, request,
        # progress announce). Drives churn: a conn slot is a scarce
        # resource and an idle-useless conn on a full seeder wedges flash
        # crowds (everyone else is soft-blacklisted waiting for a slot).
        self.last_useful = now
        self.serving = 0  # concurrent _serve_piece tasks (flood bound)
        self.receiving = 0  # concurrent payload tasks (inbound flood bound)


class Dispatcher:
    """Piece-exchange engine for one torrent.

    ``on_peer_failure(peer_id, reason)`` feeds the scheduler's blacklist;
    ``done`` resolves when the torrent completes (immediately for seeders).
    """

    def __init__(
        self,
        torrent: Torrent,
        requests: RequestManager | None = None,
        on_peer_failure: Callable[[PeerID, str], None] | None = None,
        churn_idle_seconds: float = 4.0,
        events: Producer | None = None,  # swarm tracing
        on_peer_exchange: Callable[[PeerID, dict], None] | None = None,
    ):
        self.torrent = torrent
        self.requests = requests or RequestManager()
        self.churn_idle = churn_idle_seconds
        self.events = events or NoopProducer()
        self._on_peer_failure = on_peer_failure or (lambda p, r: None)
        # PEX sink (scheduler's _on_pex): SYNC -- called from _handle on
        # the recv pump, so it must not await. Raising ValueError on a
        # malformed frame feeds the standard _fail_peer ban path.
        self._on_peer_exchange = on_peer_exchange or (lambda p, h: None)
        self._peers: dict[PeerID, _Peer] = {}
        self._io_tasks: set[asyncio.Task] = set()
        # get_running_loop, not the deprecated get_event_loop: under a
        # non-running loop on 3.12+ the latter raises (and before that
        # could bind the future to a loop the scheduler never runs).
        self.done: asyncio.Future[None] = (
            asyncio.get_running_loop().create_future()
        )
        # Per-torrent lifecycle counters for the completion summary
        # (networkevent torrent_summary -- torrentlog parity): every
        # payload byte in/out, every peer ever adopted, every
        # blacklist-feeding drop.
        self._created = asyncio.get_running_loop().time()
        self._bytes_down = 0
        self._bytes_up = 0
        # Fleet-wide swarm byte counters (cached refs: no registry lookup
        # on the per-piece path). What the delta-transfer plane's "bytes
        # actually moved" accounting reads: swarm ingress here plus the
        # planner's delta_bytes_fetched_total is every fetched byte of a
        # pull. Shard-served egress is counted separately by the worker
        # plane (data_plane_worker_bytes_sent_total).
        self._ctr_down = REGISTRY.counter(
            "p2p_piece_bytes_down_total",
            "Piece payload bytes received over the swarm wire",
        )
        self._ctr_up = REGISTRY.counter(
            "p2p_piece_bytes_up_total",
            "Piece payload bytes served over the swarm wire (main loop)",
        )
        self._peers_seen: set[PeerID] = set()
        self._blacklist_events = 0
        # Per-pull stage-timing split for the torrent_summary rollup:
        # plan (metainfo fetch + delta prefill) and dial (handshake)
        # walls are written in by the scheduler; piece_wait accumulates
        # request->payload gaps here; verify/write walls live on the
        # Torrent (storage.py). Stages overlap under pipelining -- they
        # are cumulative stage costs, not a partition of the wall.
        self.stage_walls: dict[str, float] = {"plan": 0.0, "dial": 0.0}
        self._stage_piece_wait = 0.0
        self._req_ts: dict[int, float] = {}
        # Sampler plane attribution over this torrent's life: the delta
        # of the profiler's CUMULATIVE plane counters between creation
        # and completion rides the summary, so one JSONL line answers
        # "where did THIS pull's CPU go" (utils/profiler.py tags). The
        # cumulative counter, not the ring: the ring rotates windows
        # out, and a baseline against it goes negative on any node up
        # longer than the ring span.
        from kraken_tpu.utils.profiler import PROFILER

        self._plane0 = (
            PROFILER.plane_cumulative() if PROFILER.running else None
        )
        if torrent.complete():
            self.done.set_result(None)

    # -- peer membership ---------------------------------------------------

    @property
    def num_peers(self) -> int:
        return len(self._peers)

    def peers(self) -> list[PeerID]:
        return list(self._peers)

    def add_conn(self, conn: Conn, peer_bitfield: bytes, num_pieces: int) -> bool:
        """Adopt a handshaken conn. Starts its recv pump. Returns False when
        the conn is rejected (duplicate peer or malformed bitfield) -- the
        conn is closed here and the caller must release any conn-state slot
        it reserved for it; a rejected duplicate must never tear down the
        live conn's accounting."""
        if conn.peer_id in self._peers:
            conn.close()
            return False
        try:
            has = _bits_to_set(peer_bitfield, self.torrent.num_pieces)
        except PieceError as e:
            conn.close()
            self._blacklist_events += 1  # the summary counts EVERY ban
            self._on_peer_failure(conn.peer_id, str(e))
            return False
        peer = _Peer(conn, has, asyncio.get_running_loop().time())
        self._peers[conn.peer_id] = peer
        self._peers_seen.add(conn.peer_id)
        if hasattr(conn, "set_payload_handler"):
            # Hot-path: the conn's recv loop hands PIECE_PAYLOAD frames
            # here synchronously, bypassing the recv queue + pump await
            # for the one type that carries the bytes.
            conn.set_payload_handler(
                lambda msg: self._handle_payload_direct(peer, msg)
            )
        peer.pump = asyncio.create_task(self._pump(peer))
        return True

    def _availability(self) -> dict[int, int]:
        avail: dict[int, int] = {}
        for p in self._peers.values():
            for i in p.has:
                avail[i] = avail.get(i, 0) + 1
        return avail

    def _drop_peer(self, peer_id: PeerID, reason: str | None = None) -> None:
        peer = self._peers.pop(peer_id, None)
        if peer is None:
            return
        self.requests.clear_peer(peer_id)
        peer.conn.close()
        if peer.pump is not None:
            peer.pump.cancel()
        if reason:
            self._blacklist_events += 1
            self._on_peer_failure(peer_id, reason)
        if not self._peers:
            # No live conns -> shed the cached fd (reopened on the next
            # conn's first piece IO). Bounds steady-state fd usage on
            # origins seeding many blobs.
            self.torrent.release_fd()

    def close(self) -> None:
        for pid in list(self._peers):
            self._drop_peer(pid)
        for t in list(self._io_tasks):
            t.cancel()
        if not self.done.done():
            self.done.cancel()
        # Releases the torrent's cached fd + flushes its debounced
        # bitfield so crash-resume sees the freshest persisted progress.
        self.torrent.close()

    # -- the pump ----------------------------------------------------------

    async def _pump(self, peer: _Peer) -> None:
        """Recv pump. INVARIANT: never awaits a send -- a pump blocked on a
        full send queue stops draining its recv queue, and under a swarm-
        wide burst those stalls form a cycle (distributed send/recv
        gridlock). All sending happens in _spawn_io tasks."""
        pid = peer.conn.peer_id
        try:
            self._spawn_io(peer, self._request_more(peer))
            while True:
                msg = await peer.conn.recv()
                await self._handle(peer, msg)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # defensive: one peer must not kill the loop
            self._fail_peer(pid, e)

    def _check_index(self, msg: Message) -> int:
        """Piece indices from the wire are untrusted: an out-of-range index
        is a protocol violation (drops + reports the peer), never a storage
        seek."""
        idx = msg.header.get("index")
        if not isinstance(idx, int) or not 0 <= idx < self.torrent.num_pieces:
            raise PieceError(f"piece index out of range: {idx!r}")
        return idx

    def _spawn_io(self, peer: _Peer, coro) -> asyncio.Task:
        """Run a storage-touching handler CONCURRENTLY with the recv pump.

        Serializing verify->write->next-request per piece makes every piece
        pay the full verifier batching delay (a batch of one) and blocks
        payload N+1 behind payload N's disk write; with pipeline_limit
        pieces in flight per conn the concurrency here is what lets the
        batched verifier actually batch. Failures map to the same
        drop-peer handling the pump applies (in a done callback: the task
        must wrap ``coro`` directly, or cancellation before the first step
        leaks a never-awaited coroutine)."""
        t = asyncio.create_task(coro)

        def done(task: asyncio.Task) -> None:
            self._io_tasks.discard(task)
            if task.cancelled():
                return
            exc = task.exception()
            if exc is not None:
                self._fail_peer(peer.conn.peer_id, exc)

        self._io_tasks.add(t)
        t.add_done_callback(done)
        return t

    def _fail_peer(self, pid: PeerID, exc: BaseException) -> None:
        """One exception->drop policy for the pump AND the io tasks."""
        if isinstance(exc, ConnClosedError):
            # A conn that closed itself over misbehavior (oversize
            # payload, protocol garbage flagged by the wire) must reach
            # the blacklist with its recorded reason -- a reasonless drop
            # here would let the offender redial immediately.
            peer = self._peers.get(pid)
            if peer is not None and getattr(peer.conn, "misbehavior", False):
                self._drop_peer(
                    pid,
                    f"conn misbehavior: "
                    f"{getattr(peer.conn, 'close_reason', 'unknown')}",
                )
            else:
                self._drop_peer(pid)
        elif isinstance(exc, PieceError):
            self._drop_peer(pid, f"bad piece: {exc}")
        else:
            self._drop_peer(pid, f"peer error: {exc}")

    _MAX_SERVING_PER_PEER = 32  # concurrent serve tasks; a request flood
    # beyond this is dropped (honest peers pipeline far less) -- without a
    # bound, each pending serve holds a piece-sized buffer and a hostile
    # leecher could drive a seeder to OOM.

    def _admit_serve(self, peer: _Peer, idx: int,
                     tp: str | None = None) -> None:
        """``serving`` must be bumped HERE, synchronously at admission:
        ``conn.recv()`` on already-buffered frames completes without
        yielding to the loop, so a burst of buffered PIECE_REQUESTs would
        otherwise all observe ``serving == 0`` and each spawn a task
        holding a piece-sized buffer -- exactly the flood the bound
        exists to prevent. Decrement in the task's done callback, so
        cancellation-before-first-step can't leak the slot."""
        peer.serving += 1
        t = self._spawn_io(peer, self._serve_piece(peer, idx, tp))

        def release(_task: asyncio.Task) -> None:
            peer.serving -= 1

        t.add_done_callback(release)

    def _handle_payload_direct(self, peer: _Peer, msg: Message) -> None:
        """PIECE_PAYLOAD entry called synchronously from the conn's recv
        loop (the hot-type bypass). MUST NOT await -- it runs inside the
        recv pump. Owns ``msg``'s pooled buffer from here on."""
        if self._peers.get(peer.conn.peer_id) is not peer:
            msg.release()  # raced a drop: nobody else will return it
            return
        peer.last_useful = asyncio.get_running_loop().time()
        self._spawn_payload(peer, msg)

    _MAX_RECEIVING_PER_PEER = 64  # concurrent payload tasks per conn: the
    # inbound mirror of _MAX_SERVING_PER_PEER. Each admitted payload holds
    # a piece-sized pool lease until verify+write complete, and the hot-
    # path bypass never blocks on the recv queue -- so a hostile peer
    # pushing UNSOLICITED payloads faster than the disk drains them would
    # otherwise grow leases without bound (the pool budget caps FREE
    # bytes, not live leases). Honest peers cannot reach this: their
    # in-flight payloads are request-gated at pipeline_limit (16) plus
    # bounded endgame duplicates. Over-cap frames are shed (released,
    # dropped) -- no progress for the flooder, no RSS growth for us.

    def _spawn_payload(self, peer: _Peer, msg: Message) -> None:
        """Spawn the verify->write handler for one payload frame with the
        ONE release point for its pooled buffer: the task done-callback
        fires on completion, failure, AND cancellation-before-first-step,
        so no path (corrupt-piece ban, mid-transfer disconnect, teardown)
        can leak the lease. Admission is accounted SYNCHRONOUSLY (same
        rationale as _admit_serve: buffered frames arrive without
        yielding to the loop)."""
        try:
            idx = self._check_index(msg)
        except PieceError as e:
            msg.release()
            self._fail_peer(peer.conn.peer_id, e)
            return
        if peer.receiving >= self._MAX_RECEIVING_PER_PEER:
            msg.release()
            return
        peer.receiving += 1
        t = self._spawn_io(peer, self._on_payload(peer, idx, msg))

        def release(_task: asyncio.Task) -> None:
            peer.receiving -= 1
            msg.release()

        t.add_done_callback(release)

    async def _serve_piece(self, peer: _Peer, idx: int,
                           tp: str | None = None) -> None:
        # The serve span joins the REQUESTER's trace (the PIECE_REQUEST
        # carried its traceparent only when that trace is sampled), so
        # request -> serve -> payload reads as one tree across nodes.
        parent = trace.parse_traceparent(tp)
        cm = (
            trace.span("p2p.piece.serve", parent, piece=idx,
                       peer=peer.conn.peer_id.hex[:12])
            if parent is not None else contextlib.nullcontext()
        )
        with cm:
            data = await self.torrent.read_piece_async(idx)
            await peer.conn.send(Message.piece_payload(idx, data))
        self._bytes_up += len(data)
        self._ctr_up.inc(len(data))
        # A completed send is progress: an honest-but-slow link keeps
        # earning its churn exemption one delivered piece at a time.
        peer.last_useful = asyncio.get_running_loop().time()

    async def _handle(self, peer: _Peer, msg: Message) -> None:
        if msg.type in (
            MsgType.PIECE_REQUEST, MsgType.PIECE_PAYLOAD,
            MsgType.ANNOUNCE_PIECE, MsgType.COMPLETE,
        ):
            peer.last_useful = asyncio.get_running_loop().time()
        if msg.type == MsgType.PIECE_REQUEST:
            idx = self._check_index(msg)
            if (
                self.torrent.has_piece(idx)
                and peer.serving < self._MAX_SERVING_PER_PEER
            ):
                self._admit_serve(peer, idx, msg.header.get("tp"))
        elif msg.type == MsgType.PIECE_PAYLOAD:
            # Cold path: payloads that queued before the fast-path handler
            # was registered (or in unit tests driving _handle directly).
            self._spawn_payload(peer, msg)
        elif msg.type == MsgType.ANNOUNCE_PIECE:
            peer.has.add(self._check_index(msg))
            self._spawn_io(peer, self._request_more(peer))
        elif msg.type == MsgType.BITFIELD:
            peer.has = _bits_to_set(msg.payload, self.torrent.num_pieces)
            self._spawn_io(peer, self._request_more(peer))
        elif msg.type == MsgType.COMPLETE:
            peer.complete = True
            peer.has = set(range(self.torrent.num_pieces))
            self._spawn_io(peer, self._request_more(peer))
        elif msg.type == MsgType.CANCEL_PIECE:
            pass  # best-effort: payload may already be in flight
        elif msg.type == MsgType.PEER_EXCHANGE:
            # Deliberately NOT refreshing last_useful: gossip must not
            # earn a churn exemption, or an idle peer could keep its conn
            # slot alive forever by chattering addrs.
            self._on_peer_exchange(peer.conn.peer_id, msg.header)
        elif msg.type == MsgType.ERROR:
            raise ConnClosedError(msg.header.get("detail", "peer error"))

    async def _on_payload(self, peer: _Peer, idx: int, msg: Message) -> None:
        data = msg.payload  # bytes or a pooled memoryview -- both flow
        # through verify and os.pwrite untouched; the buffer returns via
        # _spawn_payload's done-callback AFTER the bitfield mark below.
        t_req = self._req_ts.pop(idx, None)
        if t_req is not None:
            self._stage_piece_wait += (
                asyncio.get_running_loop().time() - t_req
            )
        self.events.emit(
            "receive_piece", self.torrent.info_hash.hex,
            peer=peer.conn.peer_id.hex, piece=idx, size=len(data),
        )
        self._bytes_down += len(data)
        self._ctr_down.inc(len(data))
        if self.torrent.has_piece(idx):
            self.requests.clear_piece(idx)
            await self._request_more(peer)
            return
        # Per-piece receive span (verify + pwrite) -- gated on the
        # trace's sampled flag so the data-plane hot path pays nothing
        # on unsampled pulls (the trace-on overhead band pins this).
        cm = (
            trace.span("p2p.piece.receive", piece=idx, size=len(data),
                       peer=peer.conn.peer_id.hex[:12])
            if trace.current_traceparent(sampled_only=True) is not None
            else contextlib.nullcontext()
        )
        with cm:
            # Ring-backed payloads (leech shard plane) carry a lease
            # whose remote_write pwrites in the worker that already
            # holds the bytes -- verify here reads the shared mmap
            # zero-copy, and only the verdict crosses the fork.
            rw = getattr(msg.lease, "remote_write", None)
            completed = await self.torrent.write_piece(
                idx, data, remote_write=rw
            )  # raises PieceError
        self.requests.clear_piece(idx)
        # Fan the new piece out to the swarm.
        for other in list(self._peers.values()):
            if other.conn.peer_id != peer.conn.peer_id:
                try:
                    await other.conn.send(Message.announce_piece(idx))
                except ConnClosedError:
                    pass
        if completed:
            if not self.done.done():
                self.done.set_result(None)
                self.events.emit(
                    "torrent_complete", self.torrent.info_hash.hex,
                    blob=self.torrent.metainfo.digest.hex,
                )
                # The lifecycle rollup, once, at the moment of
                # completion: bytes_up keeps counting afterwards (the
                # peer seeds on), but the download story -- how long,
                # from how many peers, against how much misbehavior --
                # is settled exactly here.
                now = asyncio.get_running_loop().time()
                self.events.emit(
                    "torrent_summary", self.torrent.info_hash.hex,
                    blob=self.torrent.metainfo.digest.hex,
                    pieces=self.torrent.num_pieces,
                    length=self.torrent.metainfo.length,
                    peers=len(self._peers_seen),
                    bytes_down=self._bytes_down,
                    bytes_up=self._bytes_up,
                    duration_s=round(now - self._created, 3),
                    blacklist_events=self._blacklist_events,
                    stages=self._stage_split(),
                    plane_split=self._plane_split(),
                )
            for other in list(self._peers.values()):
                try:
                    await other.conn.send(Message.complete())
                except ConnClosedError:
                    pass
        else:
            await self._request_more(peer)

    def stage_split(self) -> dict:
        """Public read of the per-pull stage walls (the scheduler's
        ``stage_walls`` helper serves it to the canary prober)."""
        return self._stage_split()

    def _stage_split(self) -> dict:
        """The per-pull stage walls (seconds): plan/dial from the
        scheduler, piece-wait from the request->payload gaps here,
        verify/write from the torrent's accumulators."""
        return {
            "plan_s": round(self.stage_walls.get("plan", 0.0), 3),
            "dial_s": round(self.stage_walls.get("dial", 0.0), 3),
            "piece_wait_s": round(self._stage_piece_wait, 3),
            "verify_s": round(getattr(self.torrent, "verify_wall", 0.0), 3),
            "write_s": round(getattr(self.torrent, "write_wall", 0.0), 3),
        }

    def _plane_split(self) -> dict:
        """Sampler plane-tag delta over this torrent's life (sample
        counts per plane; {} when the profiler is off)."""
        if self._plane0 is None:
            return {}
        from kraken_tpu.utils.profiler import PROFILER

        now = PROFILER.plane_cumulative()
        return {
            k: v - self._plane0.get(k, 0)
            for k, v in now.items()
            if v - self._plane0.get(k, 0) > 0
        }

    async def _request_more(self, peer: _Peer) -> None:
        if self.torrent.complete():
            return
        if self._peers.get(peer.conn.peer_id) is not peer:
            # Dropped while this task was queued: selecting now would
            # re-mark requests for a dead peer AFTER clear_peer ran,
            # ghost-blocking those pieces until the hard expiry.
            return
        chosen = self.requests.select(
            peer.conn.peer_id,
            peer.has,
            self.torrent.missing_pieces(),
            self._availability(),
        )
        if not chosen:
            return
        # On a sampled trace each request batch is a span and every
        # PIECE_REQUEST frame carries the traceparent, so the remote's
        # serve spans (dispatcher or shardpool worker) join this trace.
        tp = trace.current_traceparent(sampled_only=True)
        cm = (
            trace.span("p2p.piece.request", pieces=len(chosen),
                       peer=peer.conn.peer_id.hex[:12])
            if tp is not None else contextlib.nullcontext()
        )
        with cm as sp:
            if sp is not None:
                tp = sp.traceparent  # serve spans nest under this batch
            now = asyncio.get_running_loop().time()
            for idx in chosen:
                # First request wins the timestamp: a timeout re-request
                # must not reset the piece's wait clock.
                self._req_ts.setdefault(idx, now)
                self.events.emit(
                    "request_piece", self.torrent.info_hash.hex,
                    peer=peer.conn.peer_id.hex, piece=idx,
                )
                await peer.conn.send(Message.piece_request(idx, tp))

    # -- timers (driven by the scheduler) ----------------------------------

    async def tick(self) -> None:
        """Periodic retry + churn: re-request timed-out pieces, and close
        conns that have carried nothing useful for ``churn_idle`` seconds
        (reference conn churn: frees scarce conn slots -- on a seeder, for
        waiting leechers; on a leecher, for peers that actually have data)."""
        now = asyncio.get_running_loop().time()
        for pid, peer in list(self._peers.items()):
            idle_for = now - peer.last_useful
            if idle_for <= self.churn_idle:
                continue
            # Not idle, just slow: a piece we are mid-sending (serving) or
            # mid-receiving (outstanding request) generates no new inbound
            # messages for its whole transfer time, and dropping the conn
            # then discards live work. But the exemption is BOUNDED: a
            # peer that stops reading its socket (TCP zero window) parks
            # our sends forever with serving > 0, and an unbounded
            # exemption would let it pin a conn slot plus piece buffers
            # indefinitely. Completed serves refresh last_useful, so only
            # a link too slow to deliver one piece per 10 idle periods
            # hits the cap. (The request-pending exemption self-bounds via
            # request expiry, but the cap applies uniformly anyway.)
            active = peer.serving > 0 or bool(self.requests.pending_for(pid, now))
            if active and idle_for <= 10.0 * self.churn_idle:
                continue
            self._drop_peer(pid)  # no blacklist: idle, not misbehaving
        if self.torrent.complete():
            return
        for peer in list(self._peers.values()):
            await self._request_more(peer)

"""The P2P plane: wire protocol, conns, dispatch, scheduler, torrent storage.

Mirrors uber/kraken ``lib/torrent/*`` (SURVEY.md SS2.2): the swarm that
fans a blob out through a dynamically-formed peer mesh with piece-level
pipelining. The public surface is one blocking call --
``Scheduler.download(namespace, digest)`` -- plus seeding-by-existence for
origins. Rebuilt on asyncio: the reference's single-goroutine event loop
invariant (all torrent state owned by one thread of control) maps directly
onto a single asyncio event loop.
"""

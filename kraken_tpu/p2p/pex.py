"""Peer-exchange (PEX) gossip plane: discovery that survives tracker loss.

The tracker is the PRIMARY peer-discovery plane; PEX is the fallback
that keeps a fleet alive when every tracker is dark (bad deploy, shared
backend death, partition). Agents piggyback compact per-torrent peer
deltas on the conns they already hold: a ``PEER_EXCHANGE`` frame
(p2p/wire.py) carries ``added`` entries -- peer id, ip, LISTEN port
(handshake ``lp``; an inbound conn's transport port is ephemeral and
useless to a dialer), origin flag -- and ``dropped`` peer ids, at a
jittered interval under a per-conn send budget.

Defense model (a gossiped addr is UNTRUSTED input from a peer):

- The scheduler merges gossip into the dial set through the SAME
  connstate gate announces use -- a banned peer gossiped back in stays
  banned (``Blacklist.blocked`` wins), conn caps still apply.
- A hostile peer cannot addr-flood the dial queue: per-message entry
  caps are protocol violations beyond the hard bound (the dispatcher's
  ban path), and accepted entries still pass a token-bucket dial budget
  (sheds count on ``pex_dials_suppressed_total``).
- A seen-TTL dedup set keeps N peers gossiping the same swarm from
  re-dialing (and re-flooding maps with) the same addrs every tick.
- "dropped" is advisory and PROVENANCE-SCOPED: a sender can only
  retract entries it itself gossiped -- gossip must not evict what the
  tracker or a live handshake taught us.

The disk half: :class:`PeerCache` persists last-known dialable peers
(and each in-flight torrent's metainfo -- agents don't store metainfo
anywhere else) under ``<store>/peercache.json`` with a crash-safe
tmp+rename write, TTL-aged on load, so an agent restarted mid-outage
rejoins its swarms with zero tracker round trips.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

from kraken_tpu.core.peer import PeerID, PeerIDError, PeerInfo
from kraken_tpu.utils.bandwidth import TokenBucket
from kraken_tpu.utils.metrics import REGISTRY

# Receive-side hard bound on entries in ONE frame. The shipped send
# budget is far below it, so an honest peer can never trip it -- beyond
# it is a protocol violation (addr-flood), fed to the misbehavior ban
# path, same contract as an oversize payload.
MAX_ENTRIES_PER_MESSAGE = 256

_SRC_TRACKER = "tracker"
_SRC_CONN = "conn"
_SRC_CACHE = "cache"


@dataclasses.dataclass
class PexConfig:
    """The YAML ``pex:`` section (agent base.yaml; SIGHUP live-reloads).
    Knob table in docs/OPERATIONS.md "Tracker outage survival"."""

    # Receive + merge gossip into the dial set. Shipped ON: receiving
    # costs one map insert per fresh addr and is what lets a fleet
    # survive total tracker loss without a config push mid-outage.
    enabled: bool = True
    # Emit PEX frames on existing conns. Shipped ON with conservative
    # budgets below -- the send side is what costs bytes.
    send_enabled: bool = True
    # Gossip cadence per conn, +/- jitter fraction (desyncs the fleet;
    # a synchronized gossip tick is a self-inflicted micro-burst).
    interval_seconds: float = 30.0
    jitter: float = 0.25
    # Send budget: at most this many ADDED entries per conn per tick
    # (dropped ids ride free -- they are retractions, not load).
    max_peers_per_message: int = 16
    # Seen-TTL dedup: an addr gossiped for torrent H is not re-ingested
    # for this long (N peers all gossip the same swarm).
    seen_ttl_seconds: float = 120.0
    # Token-bucket budget on gossip-SOURCED dials (per agent): rate per
    # second with a small burst. Tracker-sourced dials are not charged.
    dial_rate: float = 10.0
    dial_burst: float = 20.0
    # Known-peers book cap per torrent (gossip + handshakes; tracker
    # entries always fit -- the tracker handout is already bounded).
    max_known_peers: int = 256
    # Disk-backed last-known-peers cache (<store>/peercache.json).
    peercache: bool = True
    peercache_ttl_seconds: float = 6 * 3600.0
    peercache_flush_seconds: float = 30.0

    @classmethod
    def from_dict(cls, doc: dict | None) -> "PexConfig":
        doc = dict(doc or {})
        allowed = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"unknown pex config keys: {sorted(unknown)}")
        return cls(**doc)


class KnownPeers:
    """Per-torrent book of dialable peers with provenance.

    Provenance guards retraction: a gossip "dropped" from sender S only
    removes entries S itself added -- never tracker/handshake/cache
    knowledge. The book is capped; when full, new GOSSIP entries are
    refused (tracker and handshake entries displace gossip ones) so a
    chatty peer cannot evict authoritative knowledge by filling it.
    """

    __slots__ = ("_peers", "_src", "cap")

    def __init__(self, cap: int = 256):
        self._peers: dict[PeerID, PeerInfo] = {}
        self._src: dict[PeerID, str] = {}
        self.cap = cap

    def __len__(self) -> int:
        return len(self._peers)

    def add(self, peer: PeerInfo, src: str) -> bool:
        pid = peer.peer_id
        if pid in self._peers:
            # Authoritative sources overwrite gossip; gossip refreshes
            # only its own entries (a peer must not "move" another's
            # tracker-recorded addr).
            cur = self._src[pid]
            if cur.startswith("gossip:") or src in (_SRC_TRACKER, _SRC_CONN):
                self._peers[pid] = peer
                self._src[pid] = src
            return True
        if len(self._peers) >= self.cap:
            if src.startswith("gossip:") or src == _SRC_CACHE:
                return False
            evicted = next(
                (p for p, s in self._src.items()
                 if s.startswith("gossip:") or s == _SRC_CACHE),
                None,
            )
            if evicted is None:
                return False
            del self._peers[evicted], self._src[evicted]
        self._peers[pid] = peer
        self._src[pid] = src
        return True

    def drop(self, pid: PeerID, src: str) -> None:
        """Provenance-scoped retraction (gossip ``dropped`` entries)."""
        if self._src.get(pid) == src:
            del self._peers[pid], self._src[pid]

    def discard(self, pid: PeerID) -> None:
        """Unconditional removal (our own dial found the addr dead)."""
        self._peers.pop(pid, None)
        self._src.pop(pid, None)

    def snapshot(self) -> list[PeerInfo]:
        return list(self._peers.values())


def _parse_entry(e) -> PeerInfo:
    """One gossiped ``added`` entry -> PeerInfo. Any shape violation is
    a ValueError: the dispatcher maps it to the peer-error ban path."""
    if not isinstance(e, dict):
        raise ValueError(f"pex entry is not a map: {type(e).__name__}")
    try:
        pid = PeerID(e["id"])
        ip = e["ip"]
        port = e["p"]
    except (KeyError, PeerIDError, TypeError) as exc:
        raise ValueError(f"malformed pex entry: {exc}") from exc
    if not isinstance(ip, str) or not 0 < len(ip) <= 64:
        raise ValueError(f"malformed pex ip: {ip!r}")
    if not isinstance(port, int) or not 0 < port < 65536:
        raise ValueError(f"malformed pex port: {port!r}")
    return PeerInfo(pid, ip, port, origin=bool(e.get("o", False)))


class PexManager:
    """Send budgets, receive validation, and the seen-TTL dedup set.

    One per scheduler. Sync throughout -- every entry point is called
    from recv pumps or the gossip tick on the event loop.
    """

    _EXPUNGE_EVERY = 512  # amortized seen-set sweep (Blacklist's idiom)

    def __init__(self, config: PexConfig | None = None):
        self.config = config or PexConfig()
        # (info_hash hex, peer id hex) -> seen-until monotonic deadline.
        self._seen: dict[tuple[str, str], float] = {}
        self._ops = 0
        self._dial_bucket = TokenBucket(
            self.config.dial_rate, self.config.dial_burst
        )
        # Per-conn sent book: conn key -> {peer id hex} we already
        # gossiped on that conn, for added/dropped delta computation.
        self._sent: dict[object, set[str]] = {}
        # Register the pex_* family eagerly: the metric catalog's
        # runtime half boots an idle-ish pair, and a metric that only
        # exists after the first gossip frame would dodge the lint.
        self._m_sent = REGISTRY.counter(
            "pex_messages_sent_total", "PEER_EXCHANGE frames sent"
        )
        self._m_recv = REGISTRY.counter(
            "pex_messages_received_total", "PEER_EXCHANGE frames received"
        )
        self._m_peers = REGISTRY.counter(
            "pex_peers_received_total",
            "Fresh dialable peers accepted from gossip (post dedup)",
        )
        self._m_suppressed = REGISTRY.counter(
            "pex_dials_suppressed_total",
            "Gossiped peers not dialed (token-bucket budget exhausted)",
        )

    def reconfigure(self, config: PexConfig) -> None:
        """SIGHUP: swap knobs live. The dial bucket is rebuilt (rate
        change); the seen set and sent books survive -- dedup state is
        correctness, not tuning."""
        self.config = config
        self._dial_bucket = TokenBucket(config.dial_rate, config.dial_burst)

    # -- receive path ------------------------------------------------------

    def ingest(
        self, h_hex: str, sender: PeerID, header: dict, now: float
    ) -> tuple[list[PeerInfo], list[PeerID]]:
        """Validate one received PEX header -> (fresh added, dropped).

        Raises ValueError on any protocol violation (shape garbage,
        entry flood) -- the caller's ban path handles it. ``added``
        peers already passed the seen-TTL dedup; the caller still owes
        them the blacklist gate and the dial budget.
        """
        self._m_recv.inc()
        added = header.get("a", [])
        dropped = header.get("d", [])
        if not isinstance(added, list) or not isinstance(dropped, list):
            raise ValueError("malformed pex frame: a/d not lists")
        if len(added) + len(dropped) > MAX_ENTRIES_PER_MESSAGE:
            raise ValueError(
                f"pex flood: {len(added) + len(dropped)} entries"
                f" (cap {MAX_ENTRIES_PER_MESSAGE})"
            )
        fresh: list[PeerInfo] = []
        for e in added:
            peer = _parse_entry(e)
            if self._fresh(h_hex, peer.peer_id.hex, now):
                fresh.append(peer)
        drops: list[PeerID] = []
        for d in dropped:
            if not isinstance(d, str):
                raise ValueError(f"malformed pex drop: {d!r}")
            try:
                drops.append(PeerID(d))
            except PeerIDError as exc:
                raise ValueError(f"malformed pex drop: {exc}") from exc
        if fresh:
            self._m_peers.inc(len(fresh))
        return fresh, drops

    def _fresh(self, h_hex: str, pid_hex: str, now: float) -> bool:
        self._ops += 1
        if self._ops % self._EXPUNGE_EVERY == 0:
            self._seen = {
                k: t for k, t in self._seen.items() if t > now
            }
        key = (h_hex, pid_hex)
        if self._seen.get(key, 0.0) > now:
            return False
        self._seen[key] = now + self.config.seen_ttl_seconds
        return True

    def try_dial_budget(self) -> bool:
        """One gossip-sourced dial admission; sheds are metered."""
        if self._dial_bucket.try_acquire(1.0):
            return True
        self._m_suppressed.inc()
        return False

    # -- send path ---------------------------------------------------------

    def delta_for(
        self, conn_key: object, recipient: PeerID, peers: list[PeerInfo]
    ) -> tuple[list[dict], list[str]]:
        """Compute this conn's next gossip delta against what we already
        sent it, capped at the send budget. ``peers`` is the torrent's
        current dialable book. Returns ([], []) when there is nothing
        new to say (the caller skips the frame entirely)."""
        sent = self._sent.setdefault(conn_key, set())
        current = {
            p.peer_id.hex: p for p in peers if p.peer_id != recipient
        }
        added_ids = [pid for pid in current if pid not in sent]
        added_ids = added_ids[: self.config.max_peers_per_message]
        dropped_ids = [pid for pid in sent if pid not in current]
        added = []
        for pid in added_ids:
            p = current[pid]
            entry = {"id": pid, "ip": p.ip, "p": p.port}
            if p.origin:
                entry["o"] = True
            added.append(entry)
        sent.update(added_ids)
        sent.difference_update(dropped_ids)
        if added:
            self._m_sent.inc()
        return added, dropped_ids

    def forget_conn(self, conn_key: object) -> None:
        self._sent.pop(conn_key, None)


class PeerCache:
    """Crash-safe disk cache of last-known peers + in-flight metainfo.

    All IO is SYNCHRONOUS -- callers hop through ``asyncio.to_thread``
    (the lint's blocking-IO-in-async rule is load-bearing here). The
    write is tmp + fsync + ``os.replace``: a crash mid-write leaves
    either the old file or a torn ``.tmp`` the next load ignores.
    """

    VERSION = 1

    def __init__(self, path: str, ttl_seconds: float = 6 * 3600.0):
        self.path = path
        self.ttl = ttl_seconds
        self._m_writes = REGISTRY.counter(
            "pex_peercache_writes_total",
            "Peercache snapshots persisted (tmp+rename)",
        )

    def load(self, now: float | None = None) -> dict[str, dict]:
        """info_hash hex -> {"namespace", "metainfo" (serialized str),
        "peers" (PeerInfo dict list)}, TTL-aged. Missing file, torn
        tmp debris, garbage JSON, and future versions all load as {} --
        the cache is an optimization, never a boot blocker."""
        now = time.time() if now is None else now
        try:
            with open(self.path, "rb") as f:
                doc = json.loads(f.read())
        except (OSError, ValueError):
            return {}
        if not isinstance(doc, dict) or doc.get("v") != self.VERSION:
            return {}
        torrents = doc.get("torrents")
        if not isinstance(torrents, dict):
            return {}
        out: dict[str, dict] = {}
        for h_hex, rec in torrents.items():
            if not isinstance(rec, dict):
                continue
            try:
                saved_at = float(rec["saved_at"])
                peers = [PeerInfo.from_dict(p) for p in rec["peers"]]
                entry = {
                    "namespace": str(rec["namespace"]),
                    "metainfo": str(rec["metainfo"]),
                    "peers": peers,
                    "saved_at": saved_at,
                }
            except (KeyError, TypeError, ValueError, PeerIDError):
                continue  # one torn record must not void the rest
            if now - saved_at > self.ttl:
                continue
            out[h_hex] = entry
        return out

    def save(
        self, torrents: dict[str, dict], now: float | None = None
    ) -> None:
        """``torrents``: info_hash hex -> {"namespace", "metainfo",
        "peers": [PeerInfo], optional "saved_at"}. Records carrying
        their own ``saved_at`` (merged back from a load) keep it, so a
        flush can carry forward a restarted agent's not-yet-requested
        torrents without resetting their TTL clocks forever. Atomic vs
        crash at every step."""
        now = time.time() if now is None else now
        doc = {
            "v": self.VERSION,
            "torrents": {
                h: {
                    "namespace": rec["namespace"],
                    "metainfo": rec["metainfo"],
                    "saved_at": rec.get("saved_at") or now,
                    "peers": [p.to_dict() for p in rec["peers"]],
                }
                for h, rec in torrents.items()
            },
        }
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._m_writes.inc()

"""Chunk-level delta transfer: pull only the bytes the cluster lacks.

The dedup plane measures 0.39-0.78 duplicate bytes across layers
(PERF.md "Dedup plane") and then the wire moves whole blobs anyway. This
module cashes the measurement in on the agent's pull path:

1. **Plan**: fetch the target blob's :class:`~kraken_tpu.core.metainfo.
   ChunkRecipe` (tracker-proxied from the origin's dedup sidecars), ask
   ``/similar`` for near-duplicate blobs, keep the candidates already in
   the local cache, and diff recipes into ``have`` spans (bytes a local
   base blob already holds) and ``need`` spans.
2. **Copy**: for every piece the base covers, copy the have-chunks out of
   the local base -- each chunk re-hashed against its recipe fingerprint
   first, so a corrupt or stale base degrades to a fetch, never into the
   assembled blob.
3. **Fetch**: pieces the base covers only partially get their need spans
   as origin byte-range GETs (the ``X-Kraken-Origin`` addr the tracker
   stamps on the recipe response); pieces with little or no coverage stay
   missing and ride the normal swarm piece pulls.

Every assembled piece goes through the UNCHANGED
:meth:`~kraken_tpu.p2p.storage.Torrent.write_piece` verify (full
per-piece SHA-256 against the metainfo), so delta is an optimization,
never a trust change: the worst a wrong recipe/base can do is waste the
copy and fall back. Prefilled progress persists through the normal piece
bitfield, so the swarm download that follows sees exactly a resumable
partial.

Default OFF (YAML ``delta:`` on agent + origin; SIGHUP live-reloads).
Knob table and rollout runbook: docs/OPERATIONS.md "Delta transfer".
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import logging
import os
from typing import NamedTuple, Protocol

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import ChunkRecipe, MetaInfo, chunk_fp
from kraken_tpu.p2p.storage import PieceError
from kraken_tpu.utils import failpoints, trace
from kraken_tpu.utils.httputil import HTTPClient, HTTPError, base_url
from kraken_tpu.utils.metrics import REGISTRY
from urllib.parse import quote

_log = logging.getLogger("kraken.p2p.delta")


@dataclasses.dataclass
class DeltaConfig:
    """The YAML ``delta:`` section (agent + origin; live-reloads via
    SIGHUP). Knob table in docs/OPERATIONS.md "Delta transfer"."""

    # Master switch. Shipped OFF: enabling delta is a rollout decision
    # (origins must serve recipes first -- see the runbook), never a
    # config-refresh surprise. On the origin this gates GET .../recipe;
    # on the agent it gates the pull-time planner.
    enabled: bool = False
    # Blobs below this skip planning outright: the recipe/similar round
    # trips cost more than they can save on small blobs. Matches the
    # shipped base.yaml value (the OPERATIONS.md knob table documents
    # both as 4 MiB).
    min_blob_bytes: int = 4 << 20
    # How many locally-held /similar candidates to diff before picking
    # the base with the most covered bytes.
    max_bases: int = 3
    # /similar candidates below this estimated Jaccard are ignored.
    min_jaccard: float = 0.1
    # A partially-covered piece is delta-assembled (local copies + range
    # GETs for the holes) only when the base covers at least this
    # fraction of it; below, the whole piece rides the swarm -- range
    # requests for slivers cost more than they save.
    min_piece_cover: float = 0.25
    # Fetch need spans of partially-covered pieces as origin byte-range
    # GETs. Off = only fully-covered pieces are delta-assembled and
    # everything else rides the swarm.
    range_fetch: bool = True

    @classmethod
    def from_dict(cls, doc: dict | None) -> "DeltaConfig":
        doc = dict(doc or {})
        allowed = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"unknown delta config keys: {sorted(unknown)}")
        return cls(**doc)


class DeltaClient(Protocol):
    """What the planner needs from the control plane (TrackerClient)."""

    async def get_recipe(
        self, namespace: str, d: Digest
    ) -> tuple[ChunkRecipe, str]: ...

    async def similar(self, namespace: str, d: Digest) -> list[dict]: ...


class HaveSpan(NamedTuple):
    """One target chunk the base also holds: copy ``size`` bytes from
    ``base_off`` in the base blob to ``target_off`` in the target, valid
    only if the copied bytes still hash to ``fp``."""

    target_off: int
    size: int
    base_off: int
    fp: int


def diff_recipes(
    target: ChunkRecipe, base: ChunkRecipe
) -> tuple[list[HaveSpan], list[tuple[int, int]]]:
    """Partition the target blob against a base: per-chunk ``have`` spans
    (fp-verifiable copies) and merged ``(offset, size)`` ``need`` spans.

    Invariant (property-tested): the have spans plus the need spans tile
    ``[0, target.length)`` exactly -- no overlap, no gap. Matching is by
    ``(fp, size)``; a fingerprint collision between different-sized
    chunks therefore cannot mispair, and a same-size collision is caught
    by the copy-time re-hash.
    """
    base_map: dict[tuple[int, int], int] = {}
    for fp, off, size in base.chunks():
        base_map.setdefault((fp, size), off)
    haves: list[HaveSpan] = []
    needs: list[tuple[int, int]] = []
    for fp, off, size in target.chunks():
        b = base_map.get((fp, size))
        if b is not None:
            haves.append(HaveSpan(off, size, b, fp))
        elif needs and needs[-1][0] + needs[-1][1] == off:
            needs[-1] = (needs[-1][0], needs[-1][1] + size)
        else:
            needs.append((off, size))
    return haves, needs


class _RangeUnsupported(Exception):
    """The origin answered 200 to a Range request: no byte-range support
    behind this URL -- disable ranged assembly for the rest of the pull."""


class DeltaPlanner:
    """Agent-side delta pull: plan -> copy -> fetch, before the swarm.

    One per node, shared by every download; ``prefill`` runs inside the
    scheduler's per-digest download coalescer, so at most one prefill per
    blob is in flight. Failures at ANY stage degrade to the normal full
    swarm pull -- the planner never fails a download.
    """

    def __init__(
        self,
        store,  # store.CAStore
        archive,  # p2p.storage.AgentTorrentArchive
        client: DeltaClient,
        config: DeltaConfig | None = None,
        http: HTTPClient | None = None,
    ):
        self.store = store
        self.archive = archive
        self.client = client
        self.config = config or DeltaConfig()
        # Ranged reads fail FAST to the swarm (retries=0): the swarm path
        # is the retry, and a struggling origin should shed this load.
        self._http = http or HTTPClient(retries=0)
        self._pulls = REGISTRY.counter(
            "delta_pulls_total",
            "Delta-planned pulls by outcome (delta = >=1 piece prefilled)",
        )
        self._copied = REGISTRY.counter(
            "delta_bytes_copied_local_total",
            "Bytes copied out of a local delta base instead of fetched",
        )
        self._fetched = REGISTRY.counter(
            "delta_bytes_fetched_total",
            "Bytes fetched as origin byte ranges for delta-assembled pieces",
        )
        self._recipe_misses = REGISTRY.counter(
            "delta_recipe_misses_total",
            "Chunk-recipe fetches that missed (disabled origin, evicted "
            "sidecar, or error), by which side of the diff",
        )
        self._chunk_rejects = REGISTRY.counter(
            "delta_chunk_verify_failures_total",
            "Base chunks whose bytes no longer hash to the recipe fp "
            "(corrupt/stale local base); the piece fell back to the swarm",
        )
        self._piece_rejects = REGISTRY.counter(
            "delta_piece_verify_failures_total",
            "Delta-assembled pieces that failed the piece-hash verify "
            "and fell back to the swarm",
        )

    async def close(self) -> None:
        await self._http.close()

    # -- plan ---------------------------------------------------------------

    async def prefill(self, metainfo: MetaInfo, namespace: str) -> dict | None:
        """Try to assemble pieces of ``metainfo`` from a local delta base
        before the swarm pull. Returns a summary dict (or None when delta
        did not apply). Never raises for plan/copy/fetch failures -- the
        caller's swarm download is the fallback for everything."""
        cfg = self.config
        d = metainfo.digest
        if (
            not cfg.enabled
            or metainfo.length < cfg.min_blob_bytes
            or self.store.in_cache(d)
        ):
            return None
        with trace.span(
            "delta.plan", digest=d.hex[:12], namespace=namespace
        ) as sp:
            try:
                target, origin_addr = await self.client.get_recipe(namespace, d)
            except Exception as e:
                self._recipe_misses.inc(side="target")
                self._pulls.inc(outcome="recipe_miss")
                _log.debug(
                    "delta: no recipe for target; full pull",
                    extra={"digest": d.hex, "error": repr(e)},
                )
                return None
            if target.length != metainfo.length:
                # A recipe that disagrees with the metainfo cannot be
                # planned against (stale sidecar vs a digest collision is
                # not worth distinguishing here -- both mean "don't").
                self._recipe_misses.inc(side="target")
                self._pulls.inc(outcome="recipe_miss")
                return None
            picked = await self._pick_base(namespace, d, target)
            if picked is None:
                self._pulls.inc(outcome="no_base")
                return None
            base_d, haves = picked
            if sp is not None:
                sp.set(
                    base=base_d.hex[:12],
                    have_bytes=sum(h.size for h in haves),
                )
        if failpoints.fire("p2p.delta.base.evict"):
            # Model cache eviction racing the plan->copy window: the base
            # bytes vanish under the planner, which must fall back to the
            # full swarm pull cleanly (tests/test_delta.py chaos tier).
            self.store.delete_cache_file(base_d)
        result = {
            "base": base_d.hex,
            "pieces": 0,
            "copied": 0,
            "fetched": 0,
        }
        torrent = self.archive.create_torrent(metainfo)
        try:
            if not torrent.complete():
                await self._assemble(
                    torrent, metainfo, namespace, base_d, haves,
                    origin_addr, result,
                )
                # Hand progress over NOW: the scheduler builds a fresh
                # Torrent from the persisted bitfield immediately after,
                # and the debounced flusher's window would lose pieces.
                await torrent.flush_bits()
        finally:
            torrent.close()
        self._pulls.inc(outcome="delta" if result["pieces"] else "no_cover")
        self._copied.inc(result["copied"])
        self._fetched.inc(result["fetched"])
        _log.info(
            "delta prefill",
            extra={
                "digest": d.hex,
                "base": base_d.hex,
                "pieces": result["pieces"],
                "copied_bytes": result["copied"],
                "fetched_bytes": result["fetched"],
            },
        )
        return result

    async def _pick_base(
        self, namespace: str, d: Digest, target: ChunkRecipe
    ) -> tuple[Digest, list[HaveSpan]] | None:
        """Best locally-held /similar candidate by covered bytes."""
        try:
            sims = await self.client.similar(namespace, d)
        except Exception as e:
            _log.debug(
                "delta: /similar unavailable; full pull",
                extra={"digest": d.hex, "error": repr(e)},
            )
            return None
        best: tuple[Digest, list[HaveSpan]] | None = None
        best_cover = 0
        tried = 0
        for s in sims:
            try:
                score = float(s.get("score", 0.0))
                base_d = Digest.from_hex(s["digest"])
            except (KeyError, TypeError, ValueError):
                continue
            if score < self.config.min_jaccard:
                continue
            if not self.store.in_cache(base_d):
                continue
            tried += 1
            if tried > self.config.max_bases:
                break
            try:
                base_recipe, _addr = await self.client.get_recipe(
                    namespace, base_d
                )
            except Exception:
                self._recipe_misses.inc(side="base")
                continue
            haves, _needs = diff_recipes(target, base_recipe)
            cover = sum(h.size for h in haves)
            if cover > best_cover:
                best, best_cover = (base_d, haves), cover
        return best if best_cover > 0 else None

    # -- copy + fetch -------------------------------------------------------

    async def _assemble(
        self,
        torrent,
        metainfo: MetaInfo,
        namespace: str,
        base_d: Digest,
        haves: list[HaveSpan],
        origin_addr: str,
        result: dict,
    ) -> None:
        plen = metainfo.piece_length
        cover: dict[int, list[HaveSpan]] = {}
        for h in haves:
            first = h.target_off // plen
            last = (h.target_off + h.size - 1) // plen
            for i in range(first, last + 1):
                cover.setdefault(i, []).append(h)
        ranged_ok = bool(origin_addr) and self.config.range_fetch
        url = (
            f"{base_url(origin_addr)}/namespace/"
            f"{quote(namespace, safe='')}/blobs/{metainfo.digest.hex}"
            if origin_addr
            else ""
        )
        try:
            base_fd = self.store.open_cache_fd(base_d)
        except KeyError:
            # Base evicted between plan and copy: nothing to copy -- the
            # swarm takes the whole pull. (An eviction AFTER this open is
            # harmless: the fd pins the immutable bytes past the unlink.)
            _log.debug(
                "delta: base evicted before copy; full pull",
                extra={"base": base_d.hex},
            )
            return
        # Per-chunk verify verdicts, shared across pieces: a chunk that
        # straddles a piece boundary is read+hashed once, not once per
        # piece, and a corrupt one is counted once. _copy_piece calls
        # run one at a time (awaited below), so no locking.
        verified: dict[HaveSpan, bool] = {}
        try:
            with trace.span(
                "delta.copy", digest=metainfo.digest.hex[:12],
                base=base_d.hex[:12],
            ):
                for i in torrent.missing_pieces():
                    spans = cover.get(i)
                    if not spans:
                        continue
                    p0 = i * plen
                    pl = metainfo.piece_length_of(i)
                    out = await asyncio.to_thread(
                        self._copy_piece, base_fd, p0, pl, spans, verified
                    )
                    if out is None:
                        continue  # fp reject: this piece rides the swarm
                    buf, holes, copied = out
                    if holes:
                        if (
                            not ranged_ok
                            or copied < self.config.min_piece_cover * pl
                        ):
                            continue
                        try:
                            with trace.span(
                                "delta.fetch", piece=i, spans=len(holes),
                            ):
                                fetched = await self._fetch_holes(
                                    url, p0, holes, buf
                                )
                        except _RangeUnsupported:
                            ranged_ok = False
                            continue
                        except Exception as e:
                            # ONE failure budget for the whole pull: a
                            # dead/partitioned origin must not be
                            # re-dialed (and re-timed-out) per piece --
                            # serial 60 s stalls inside prefill would
                            # make delta slower than the swarm it is
                            # supposed to beat. Fully-covered pieces
                            # still assemble; the rest ride the swarm.
                            ranged_ok = False
                            _log.debug(
                                "delta: range fetch failed; ranged "
                                "assembly off for this pull",
                                extra={"piece": i, "error": repr(e)},
                            )
                            continue
                        result["fetched"] += fetched
                    try:
                        await torrent.write_piece(i, bytes(buf))
                    except PieceError:
                        # The assembled piece does not hash to the
                        # metainfo (stale recipe, fp collision): the
                        # unchanged verify caught it; swarm re-fetches.
                        self._piece_rejects.inc()
                        continue
                    result["copied"] += copied
                    result["pieces"] += 1
        finally:
            os.close(base_fd)

    def _copy_piece(
        self,
        base_fd: int,
        p0: int,
        pl: int,
        spans: list[HaveSpan],
        verified: dict[HaveSpan, bool],
    ) -> tuple[bytearray, list[tuple[int, int]], int] | None:
        """Build piece ``[p0, p0+pl)`` from base chunks (worker thread).

        Returns ``(buf, holes, copied_bytes)`` where ``holes`` are the
        piece-relative ``(off, size)`` intervals no verified chunk
        covered, or None when a chunk failed its fp re-verify (corrupt
        base: the piece must not be assembled from it). ``verified``
        carries per-chunk verdicts across this pull's pieces: a chunk
        straddling a piece boundary is fully read + hashed by the first
        piece that sees it, and later pieces read only their overlap."""
        buf = bytearray(pl)
        filled: list[tuple[int, int]] = []
        copied = 0
        for h in spans:
            lo = max(h.target_off, p0)
            hi = min(h.target_off + h.size, p0 + pl)
            if lo >= hi:
                continue
            ok = verified.get(h)
            if ok is False:
                return None
            if ok is None:
                chunk = os.pread(base_fd, h.size, h.base_off)
                if len(chunk) != h.size or chunk_fp(chunk) != h.fp:
                    # The base no longer holds what the recipe says
                    # (at-rest corruption, or a recipe/blob mismatch):
                    # nothing copied from it can be trusted.
                    self._chunk_rejects.inc()
                    verified[h] = False
                    return None
                verified[h] = True
                part = chunk[lo - h.target_off : hi - h.target_off]
            else:
                # Verified by an earlier piece: read just the overlap.
                part = os.pread(
                    base_fd, hi - lo, h.base_off + (lo - h.target_off)
                )
                if len(part) != hi - lo:
                    # Immutable-CAS fds can't short-read inside the file;
                    # treat anything else as a reject, not silent holes.
                    self._chunk_rejects.inc()
                    verified[h] = False
                    return None
            rel = lo - p0
            buf[rel : rel + (hi - lo)] = part
            filled.append((rel, hi - lo))
            copied += hi - lo
        filled.sort()
        holes: list[tuple[int, int]] = []
        pos = 0
        for off, size in filled:
            if off > pos:
                holes.append((pos, off - pos))
            pos = max(pos, off + size)
        if pos < pl:
            holes.append((pos, pl - pos))
        return buf, holes, copied

    # Concurrent Range GETs per piece: build-over-build coverage
    # alternates have/need, so a piece often carries several holes --
    # fetching them serially costs sum(holes) x RTT on a WAN origin.
    _FETCH_CONCURRENCY = 4

    async def _fetch_holes(
        self,
        url: str,
        p0: int,
        holes: list[tuple[int, int]],
        buf: bytearray,
    ) -> int:
        """Fill ``holes`` (piece-relative) of ``buf`` via origin Range
        GETs (up to ``_FETCH_CONCURRENCY`` in flight); returns bytes
        fetched. Raises :class:`_RangeUnsupported` when the origin
        answers 200 (whole blob) to a range request; that error wins
        over transient ones so the caller turns ranging off rather than
        retrying an origin that will never serve spans."""
        sem = asyncio.Semaphore(self._FETCH_CONCURRENCY)

        async def fetch_one(rel: int, size: int) -> int:
            a = p0 + rel
            async with sem:
                try:
                    body = await self._http.get(
                        url,
                        headers={"Range": f"bytes={a}-{a + size - 1}"},
                        ok_statuses=(206,),
                        # 200 = no range support behind this URL. Abort
                        # (no body read) instead of buffering the WHOLE
                        # blob just to learn it can't serve spans.
                        abort_statuses=(200,),
                        retry_5xx=False,
                    )
                except HTTPError as e:
                    if e.status == 200:
                        raise _RangeUnsupported(url) from None
                    raise
            if len(body) != size:
                raise PieceError(
                    f"range GET returned {len(body)} bytes, wanted {size}"
                )
            buf[rel : rel + size] = body
            return size

        results = await asyncio.gather(
            *(fetch_one(rel, size) for rel, size in holes),
            return_exceptions=True,
        )
        errs = [r for r in results if isinstance(r, BaseException)]
        for e in errs:
            if isinstance(e, _RangeUnsupported):
                raise e
        if errs:
            raise errs[0]
        return sum(results)

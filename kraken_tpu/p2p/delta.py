"""Chunk-level delta transfer: pull only the bytes the cluster lacks.

The dedup plane measures 0.39-0.78 duplicate bytes across layers
(PERF.md "Dedup plane") and then the wire moves whole blobs anyway. This
module cashes the measurement in on the agent's pull path:

1. **Plan**: fetch the target blob's :class:`~kraken_tpu.core.metainfo.
   ChunkRecipe` (tracker-proxied from the origin's dedup sidecars), ask
   ``/similar`` for near-duplicate blobs, keep the candidates already in
   the local cache, and diff recipes into ``have`` spans (bytes a local
   base blob already holds) and ``need`` spans.
2. **Copy**: for every piece the base covers, copy the have-chunks out of
   the local base -- each chunk re-hashed against its recipe fingerprint
   first, so a corrupt or stale base degrades to a fetch, never into the
   assembled blob.
3. **Fetch**: pieces the base covers only partially get their need spans
   as origin byte-range GETs (the ``X-Kraken-Origin`` addr the tracker
   stamps on the recipe response); pieces with little or no coverage stay
   missing and ride the normal swarm piece pulls.

Every assembled piece goes through the UNCHANGED
:meth:`~kraken_tpu.p2p.storage.Torrent.write_piece` verify (full
per-piece SHA-256 against the metainfo), so delta is an optimization,
never a trust change: the worst a wrong recipe/base can do is waste the
copy and fall back. Prefilled progress persists through the normal piece
bitfield, so the swarm download that follows sees exactly a resumable
partial.

Default OFF (YAML ``delta:`` on agent + origin; SIGHUP live-reloads).
Knob table and rollout runbook: docs/OPERATIONS.md "Delta transfer".
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import logging
from typing import NamedTuple, Protocol

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import ChunkRecipe, MetaInfo, chunk_fp
from kraken_tpu.p2p.storage import PieceError
from kraken_tpu.utils import failpoints, trace
from kraken_tpu.utils.httputil import HTTPClient, HTTPError, base_url
from kraken_tpu.utils.metrics import REGISTRY
from urllib.parse import quote

_log = logging.getLogger("kraken.p2p.delta")


@dataclasses.dataclass
class DeltaConfig:
    """The YAML ``delta:`` section (agent + origin; live-reloads via
    SIGHUP). Knob table in docs/OPERATIONS.md "Delta transfer"."""

    # Master switch. Shipped OFF: enabling delta is a rollout decision
    # (origins must serve recipes first -- see the runbook), never a
    # config-refresh surprise. On the origin this gates GET .../recipe;
    # on the agent it gates the pull-time planner.
    enabled: bool = False
    # Blobs below this skip planning outright: the recipe/similar round
    # trips cost more than they can save on small blobs. Matches the
    # shipped base.yaml value (the OPERATIONS.md knob table documents
    # both as 4 MiB).
    min_blob_bytes: int = 4 << 20
    # How many locally-held /similar candidates to diff before picking
    # the base with the most covered bytes.
    max_bases: int = 3
    # /similar candidates below this estimated Jaccard are ignored.
    min_jaccard: float = 0.1
    # A partially-covered piece is delta-assembled (local copies + range
    # GETs for the holes) only when the base covers at least this
    # fraction of it; below, the whole piece rides the swarm -- range
    # requests for slivers cost more than they save.
    min_piece_cover: float = 0.25
    # Fetch need spans of partially-covered pieces as origin byte-range
    # GETs. Off = only fully-covered pieces are delta-assembled and
    # everything else rides the swarm.
    range_fetch: bool = True

    @classmethod
    def from_dict(cls, doc: dict | None) -> "DeltaConfig":
        doc = dict(doc or {})
        allowed = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"unknown delta config keys: {sorted(unknown)}")
        return cls(**doc)


class DeltaClient(Protocol):
    """What the planner needs from the control plane (TrackerClient)."""

    async def get_recipe(
        self, namespace: str, d: Digest
    ) -> tuple[ChunkRecipe, str]: ...

    async def similar(self, namespace: str, d: Digest) -> list[dict]: ...


class HaveSpan(NamedTuple):
    """One target chunk a cached base also holds: copy ``size`` bytes
    from ``base_off`` in base number ``base`` (index into the pull's
    selected-base list) to ``target_off`` in the target, valid only if
    the copied bytes still hash to ``fp``."""

    target_off: int
    size: int
    base_off: int
    fp: int
    base: int = 0


def diff_recipes(
    target: ChunkRecipe, base: ChunkRecipe
) -> tuple[list[HaveSpan], list[tuple[int, int]]]:
    """Partition the target blob against ONE base: per-chunk ``have``
    spans (fp-verifiable copies) and merged ``(offset, size)`` ``need``
    spans. The single-base view of :func:`diff_recipes_multi`.

    Invariant (property-tested): the have spans plus the need spans tile
    ``[0, target.length)`` exactly -- no overlap, no gap. Matching is by
    ``(fp, size)``; a fingerprint collision between different-sized
    chunks therefore cannot mispair, and a same-size collision is caught
    by the copy-time re-hash.
    """
    return diff_recipes_multi(target, [base])


def diff_recipes_multi(
    target: ChunkRecipe, bases: list[ChunkRecipe]
) -> tuple[list[HaveSpan], list[tuple[int, int]]]:
    """Partition the target against the UNION of several bases: each
    target chunk copies from the first base (in list order) that holds
    its ``(fp, size)``; chunks no base holds merge into need spans. The
    same tiling invariant as the single-base diff, property-tested over
    both."""
    base_map: dict[tuple[int, int], tuple[int, int]] = {}
    for i, base in enumerate(bases):
        for fp, off, size in base.chunks():
            base_map.setdefault((fp, size), (i, off))
    haves: list[HaveSpan] = []
    needs: list[tuple[int, int]] = []
    for fp, off, size in target.chunks():
        b = base_map.get((fp, size))
        if b is not None:
            haves.append(HaveSpan(off, size, b[1], fp, b[0]))
        elif needs and needs[-1][0] + needs[-1][1] == off:
            needs[-1] = (needs[-1][0], needs[-1][1] + size)
        else:
            needs.append((off, size))
    return haves, needs


def pick_cover_bases(
    target: ChunkRecipe,
    candidates: list[tuple[Digest, ChunkRecipe]],
    max_bases: int,
) -> list[tuple[Digest, ChunkRecipe]]:
    """Greedy set-cover over recipe fps: repeatedly take the candidate
    adding the most not-yet-covered target bytes, stop at ``max_bases``
    or zero marginal gain. Build-over-build corpora split shared content
    across SEVERAL cached prior builds -- union coverage is the ROADMAP
    ceiling (0.25-0.51 vs 0.16-0.28 single-base on the headline corpus).
    Greedy is the classic ln(n)-approximation and exact for the common
    two-base case."""
    remaining: dict[tuple[int, int], int] = {}
    for fp, _off, size in target.chunks():
        key = (fp, size)
        remaining[key] = remaining.get(key, 0) + size
    cand_keys = [
        (d, recipe, {(fp, size) for fp, _o, size in recipe.chunks()})
        for d, recipe in candidates
    ]
    picked: list[tuple[Digest, ChunkRecipe]] = []
    while len(picked) < max_bases and cand_keys and remaining:
        best_i, best_gain = -1, 0
        for i, (_d, _r, keys) in enumerate(cand_keys):
            gain = sum(remaining.get(k, 0) for k in keys)
            if gain > best_gain:
                best_i, best_gain = i, gain
        if best_i < 0:
            break
        d, recipe, keys = cand_keys.pop(best_i)
        picked.append((d, recipe))
        for k in keys:
            remaining.pop(k, None)
    return picked


class _RangeUnsupported(Exception):
    """The origin answered 200 to a Range request: no byte-range support
    behind this URL -- disable ranged assembly for the rest of the pull."""


class DeltaPlanner:
    """Agent-side delta pull: plan -> copy -> fetch, before the swarm.

    One per node, shared by every download; ``prefill`` runs inside the
    scheduler's per-digest download coalescer, so at most one prefill per
    blob is in flight. Failures at ANY stage degrade to the normal full
    swarm pull -- the planner never fails a download.
    """

    def __init__(
        self,
        store,  # store.CAStore
        archive,  # p2p.storage.AgentTorrentArchive
        client: DeltaClient,
        config: DeltaConfig | None = None,
        http: HTTPClient | None = None,
    ):
        self.store = store
        self.archive = archive
        self.client = client
        self.config = config or DeltaConfig()
        # Ranged reads fail FAST to the swarm (retries=0): the swarm path
        # is the retry, and a struggling origin should shed this load.
        self._http = http or HTTPClient(retries=0)
        self._pulls = REGISTRY.counter(
            "delta_pulls_total",
            "Delta-planned pulls by outcome (delta = >=1 piece prefilled)",
        )
        self._copied = REGISTRY.counter(
            "delta_bytes_copied_local_total",
            "Bytes copied out of a local delta base instead of fetched",
        )
        self._fetched = REGISTRY.counter(
            "delta_bytes_fetched_total",
            "Bytes fetched as origin byte ranges for delta-assembled pieces",
        )
        self._recipe_misses = REGISTRY.counter(
            "delta_recipe_misses_total",
            "Chunk-recipe fetches that missed (disabled origin, evicted "
            "sidecar, or error), by which side of the diff",
        )
        self._chunk_rejects = REGISTRY.counter(
            "delta_chunk_verify_failures_total",
            "Base chunks whose bytes no longer hash to the recipe fp "
            "(corrupt/stale local base); the piece fell back to the swarm",
        )
        self._piece_rejects = REGISTRY.counter(
            "delta_piece_verify_failures_total",
            "Delta-assembled pieces that failed the piece-hash verify "
            "and fell back to the swarm",
        )
        self._bases_used = REGISTRY.counter(
            "delta_bases_used_total",
            "Cached near-duplicate bases the multi-base planner copied "
            "from, summed over delta pulls (>1 per pull = union cover)",
        )
        self._converts = REGISTRY.counter(
            "chunkstore_converts_total",
            "Completed pulls converted to manifest + refcounted chunks, "
            "by outcome (converted / skipped / mismatch / error)",
        )
        # Recipes this planner fetched recently, kept for the chunk-tier
        # handover: a completed pull converts to manifest + chunks using
        # the SAME table the plan used -- no re-fetch, no re-chunk.
        self._recipes: dict[str, ChunkRecipe] = {}

    _RECIPE_KEEP = 128

    def _remember_recipe(self, recipe: ChunkRecipe) -> None:
        self._recipes[recipe.digest.hex] = recipe
        while len(self._recipes) > self._RECIPE_KEEP:
            self._recipes.pop(next(iter(self._recipes)))

    async def close(self) -> None:
        await self._http.close()

    # -- plan ---------------------------------------------------------------

    async def prefill(self, metainfo: MetaInfo, namespace: str) -> dict | None:
        """Try to assemble pieces of ``metainfo`` from a local delta base
        before the swarm pull. Returns a summary dict (or None when delta
        did not apply). Never raises for plan/copy/fetch failures -- the
        caller's swarm download is the fallback for everything."""
        cfg = self.config
        d = metainfo.digest
        if (
            not cfg.enabled
            or metainfo.length < cfg.min_blob_bytes
            or self.store.in_cache(d)
        ):
            return None
        with trace.span(
            "delta.plan", digest=d.hex[:12], namespace=namespace
        ) as sp:
            try:
                target, origin_addr = await self.client.get_recipe(namespace, d)
            except Exception as e:
                self._recipe_misses.inc(side="target")
                self._pulls.inc(outcome="recipe_miss")
                _log.debug(
                    "delta: no recipe for target; full pull",
                    extra={"digest": d.hex, "error": repr(e)},
                )
                return None
            if target.length != metainfo.length:
                # A recipe that disagrees with the metainfo cannot be
                # planned against (stale sidecar vs a digest collision is
                # not worth distinguishing here -- both mean "don't").
                self._recipe_misses.inc(side="target")
                self._pulls.inc(outcome="recipe_miss")
                return None
            # Remember the validated recipe for the chunk-tier handover
            # (chunk_completed) -- even a no-base first pull converts.
            self._remember_recipe(target)
            picked = await self._pick_bases(namespace, d, target)
            if not picked:
                self._pulls.inc(outcome="no_base")
                return None
            bases = [b for b, _r in picked]
            haves, _needs = diff_recipes_multi(
                target, [r for _b, r in picked]
            )
            if sp is not None:
                sp.set(
                    base=bases[0].hex[:12],
                    bases=len(bases),
                    have_bytes=sum(h.size for h in haves),
                )
        if failpoints.fire("p2p.delta.base.evict"):
            # Model cache eviction racing the plan->copy window: the base
            # bytes vanish under the planner, which must fall back to the
            # full swarm pull cleanly (tests/test_delta.py chaos tier).
            for b in bases:
                self.store.delete_cache_file(b)
        result = {
            "base": bases[0].hex,
            "bases": [b.hex for b in bases],
            "bases_used": 0,
            "pieces": 0,
            "copied": 0,
            "fetched": 0,
        }
        torrent = self.archive.create_torrent(metainfo)
        try:
            if not torrent.complete():
                await self._assemble(
                    torrent, metainfo, namespace, bases, haves,
                    origin_addr, result,
                )
                # Hand progress over NOW: the scheduler builds a fresh
                # Torrent from the persisted bitfield immediately after,
                # and the debounced flusher's window would lose pieces.
                await torrent.flush_bits()
        finally:
            torrent.close()
        self._pulls.inc(outcome="delta" if result["pieces"] else "no_cover")
        self._copied.inc(result["copied"])
        self._fetched.inc(result["fetched"])
        self._bases_used.inc(result["bases_used"])
        _log.info(
            "delta prefill",
            extra={
                "digest": d.hex,
                "bases": result["bases"],
                "bases_used": result["bases_used"],
                "pieces": result["pieces"],
                "copied_bytes": result["copied"],
                "fetched_bytes": result["fetched"],
            },
        )
        return result

    async def _pick_bases(
        self, namespace: str, d: Digest, target: ChunkRecipe
    ) -> list[tuple[Digest, ChunkRecipe]]:
        """Locally-held /similar candidates, greedy set-cover selected.

        Up to ``2 * max_bases`` cached candidates fetch recipes (the
        selection needs slack to beat best-of-N), then
        :func:`pick_cover_bases` keeps the ``max_bases`` whose UNION
        covers the most target bytes -- several prior builds each
        holding a different slice of the target beat the single best
        base (ROADMAP item 2's multi-base ceiling). Candidates whose
        manifest/recipe fetch fails just drop out; zero usable
        candidates = full pull."""
        try:
            sims = await self.client.similar(namespace, d)
        except Exception as e:
            _log.debug(
                "delta: /similar unavailable; full pull",
                extra={"digest": d.hex, "error": repr(e)},
            )
            return []
        candidates: list[tuple[Digest, ChunkRecipe]] = []
        for s in sims:  # kt-lint: disable=retry-without-deadline  # bounded to 2*max_bases local candidates; each recipe fetch is ONE budgeted HTTPClient request and a failure drops the candidate, never retries
            try:
                score = float(s.get("score", 0.0))
                base_d = Digest.from_hex(s["digest"])
            except (KeyError, TypeError, ValueError):
                continue
            if score < self.config.min_jaccard:
                continue
            if not self.store.in_cache(base_d):
                continue
            if len(candidates) >= 2 * self.config.max_bases:
                break
            try:
                base_recipe, _addr = await self.client.get_recipe(
                    namespace, base_d
                )
            except Exception:
                self._recipe_misses.inc(side="base")
                continue
            candidates.append((base_d, base_recipe))
        return pick_cover_bases(target, candidates, self.config.max_bases)

    # -- chunk-tier handover ------------------------------------------------

    async def chunk_completed(self, metainfo: MetaInfo, namespace: str) -> dict | None:
        """Convert a just-completed pull into the chunk tier (manifest +
        refcounted chunks) using the recipe the prefill fetched -- the
        scheduler calls this after every download when the tier is
        enabled. A near-duplicate of a cached build then stores only its
        unique chunks at rest, and the flat file the swarm wrote is
        dropped. Failures (recipe absent, fp/byte mismatch, tier IO)
        leave the blob flat -- conversion is an optimization, never a
        durability change."""
        cs = getattr(self.store, "chunkstore", None)
        if cs is None or not cs.config.enabled:
            return None
        d = metainfo.digest
        if metainfo.length < cs.config.min_blob_bytes:
            return None
        recipe = self._recipes.get(d.hex)
        if recipe is None or recipe.length != metainfo.length:
            return None
        with trace.span(
            "delta.chunk_convert", digest=d.hex[:12], namespace=namespace
        ):
            try:
                res = await asyncio.to_thread(
                    self.store.convert_to_chunks,
                    d, list(recipe.fps), list(recipe.sizes),
                )
            except Exception:
                self._converts.inc(outcome="error")
                raise
        if res is None:
            # Absent / already chunked / recipe-byte mismatch: the
            # store kept whichever representation it had.
            self._converts.inc(outcome="mismatch")
            return None
        self._converts.inc(outcome="converted")
        _log.info(
            "blob converted to chunk tier",
            extra={
                "digest": d.hex,
                "new_bytes": res["new_bytes"],
                "dup_bytes": res["dup_bytes"],
            },
        )
        return res

    # -- copy + fetch -------------------------------------------------------

    async def _assemble(
        self,
        torrent,
        metainfo: MetaInfo,
        namespace: str,
        bases: list[Digest],
        haves: list[HaveSpan],
        origin_addr: str,
        result: dict,
    ) -> None:
        plen = metainfo.piece_length
        cover: dict[int, list[HaveSpan]] = {}
        for h in haves:
            first = h.target_off // plen
            last = (h.target_off + h.size - 1) // plen
            for i in range(first, last + 1):
                cover.setdefault(i, []).append(h)
        ranged_ok = bool(origin_addr) and self.config.range_fetch
        url = (
            f"{base_url(origin_addr)}/namespace/"
            f"{quote(namespace, safe='')}/blobs/{metainfo.digest.hex}"
            if origin_addr
            else ""
        )
        # Per-base reader lifecycle: one positional-read handle per
        # selected base, opened up front, closed in the finally. A base
        # evicted between plan and copy just drops out (its spans'
        # pieces ride the swarm; spans of the surviving bases still
        # copy). open_cache_reader composes over BOTH representations,
        # so a base already living in the chunk tier serves copies too.
        readers: list = []
        alive = 0
        for b in bases:
            try:
                readers.append(self.store.open_cache_reader(b))
                alive += 1
            except KeyError:
                readers.append(None)
                _log.debug(
                    "delta: base evicted before copy",
                    extra={"base": b.hex},
                )
        if alive == 0:
            return
        result["bases_used"] = alive
        # Per-chunk verify verdicts, shared across pieces: a chunk that
        # straddles a piece boundary is read+hashed once, not once per
        # piece, and a corrupt one is counted once. _copy_piece calls
        # run one at a time (awaited below), so no locking.
        verified: dict[HaveSpan, bool] = {}
        try:
            with trace.span(
                "delta.copy", digest=metainfo.digest.hex[:12],
                base=bases[0].hex[:12], bases=len(bases),
            ):
                for i in torrent.missing_pieces():
                    spans = cover.get(i)
                    if not spans:
                        continue
                    p0 = i * plen
                    pl = metainfo.piece_length_of(i)
                    out = await asyncio.to_thread(
                        self._copy_piece, readers, p0, pl, spans, verified
                    )
                    if out is None:
                        continue  # fp reject: this piece rides the swarm
                    buf, holes, copied = out
                    if holes:
                        if (
                            not ranged_ok
                            or copied < self.config.min_piece_cover * pl
                        ):
                            continue
                        try:
                            with trace.span(
                                "delta.fetch", piece=i, spans=len(holes),
                            ):
                                fetched = await self._fetch_holes(
                                    url, p0, holes, buf
                                )
                        except _RangeUnsupported:
                            ranged_ok = False
                            continue
                        except Exception as e:
                            # ONE failure budget for the whole pull: a
                            # dead/partitioned origin must not be
                            # re-dialed (and re-timed-out) per piece --
                            # serial 60 s stalls inside prefill would
                            # make delta slower than the swarm it is
                            # supposed to beat. Fully-covered pieces
                            # still assemble; the rest ride the swarm.
                            ranged_ok = False
                            _log.debug(
                                "delta: range fetch failed; ranged "
                                "assembly off for this pull",
                                extra={"piece": i, "error": repr(e)},
                            )
                            continue
                        result["fetched"] += fetched
                    try:
                        await torrent.write_piece(i, bytes(buf))
                    except PieceError:
                        # The assembled piece does not hash to the
                        # metainfo (stale recipe, fp collision): the
                        # unchanged verify caught it; swarm re-fetches.
                        self._piece_rejects.inc()
                        continue
                    result["copied"] += copied
                    result["pieces"] += 1
        finally:
            for r in readers:
                if r is not None:
                    r.close()

    def _copy_piece(
        self,
        readers: list,
        p0: int,
        pl: int,
        spans: list[HaveSpan],
        verified: dict[HaveSpan, bool],
    ) -> tuple[bytearray, list[tuple[int, int]], int] | None:
        """Build piece ``[p0, p0+pl)`` from base chunks (worker thread).

        ``readers[h.base]`` is the span's base handle (None = that base
        was evicted before copy; its spans reject so the piece rides the
        swarm). Returns ``(buf, holes, copied_bytes)`` where ``holes``
        are the piece-relative ``(off, size)`` intervals no verified
        chunk covered, or None when a chunk failed its fp re-verify
        (corrupt base: the piece must not be assembled from it).
        ``verified`` carries per-chunk verdicts across this pull's
        pieces: a chunk straddling a piece boundary is fully read +
        hashed by the first piece that sees it, and later pieces read
        only their overlap."""
        buf = bytearray(pl)
        filled: list[tuple[int, int]] = []
        copied = 0
        for h in spans:
            lo = max(h.target_off, p0)
            hi = min(h.target_off + h.size, p0 + pl)
            if lo >= hi:
                continue
            ok = verified.get(h)
            if ok is False:
                return None
            reader = readers[h.base] if h.base < len(readers) else None
            if reader is None:
                return None  # base gone: this piece rides the swarm
            try:
                if ok is None:
                    chunk = reader.pread(h.size, h.base_off)
                    if len(chunk) != h.size or chunk_fp(chunk) != h.fp:
                        # The base no longer holds what the recipe says
                        # (at-rest corruption, or a recipe/blob
                        # mismatch): nothing copied from it is trusted.
                        self._chunk_rejects.inc()
                        verified[h] = False
                        return None
                    verified[h] = True
                    part = chunk[lo - h.target_off : hi - h.target_off]
                else:
                    # Verified by an earlier piece: read just the overlap.
                    part = reader.pread(
                        hi - lo, h.base_off + (lo - h.target_off)
                    )
                    if len(part) != hi - lo:
                        # Immutable-CAS reads can't short-read inside the
                        # file; treat anything else as a reject, not
                        # silent holes.
                        self._chunk_rejects.inc()
                        verified[h] = False
                        return None
            except OSError:
                # A chunk-backed base whose chunk file vanished under us
                # (quarantine race): same verdict as a failed re-hash.
                self._chunk_rejects.inc()
                verified[h] = False
                return None
            rel = lo - p0
            buf[rel : rel + (hi - lo)] = part
            filled.append((rel, hi - lo))
            copied += hi - lo
        filled.sort()
        holes: list[tuple[int, int]] = []
        pos = 0
        for off, size in filled:
            if off > pos:
                holes.append((pos, off - pos))
            pos = max(pos, off + size)
        if pos < pl:
            holes.append((pos, pl - pos))
        return buf, holes, copied

    # Concurrent Range GETs per piece: build-over-build coverage
    # alternates have/need, so a piece often carries several holes --
    # fetching them serially costs sum(holes) x RTT on a WAN origin.
    _FETCH_CONCURRENCY = 4

    async def _fetch_holes(
        self,
        url: str,
        p0: int,
        holes: list[tuple[int, int]],
        buf: bytearray,
    ) -> int:
        """Fill ``holes`` (piece-relative) of ``buf`` via origin Range
        GETs (up to ``_FETCH_CONCURRENCY`` in flight); returns bytes
        fetched. Raises :class:`_RangeUnsupported` when the origin
        answers 200 (whole blob) to a range request; that error wins
        over transient ones so the caller turns ranging off rather than
        retrying an origin that will never serve spans."""
        sem = asyncio.Semaphore(self._FETCH_CONCURRENCY)

        async def fetch_one(rel: int, size: int) -> int:
            a = p0 + rel
            async with sem:
                try:
                    body = await self._http.get(
                        url,
                        headers={"Range": f"bytes={a}-{a + size - 1}"},
                        ok_statuses=(206,),
                        # 200 = no range support behind this URL. Abort
                        # (no body read) instead of buffering the WHOLE
                        # blob just to learn it can't serve spans.
                        abort_statuses=(200,),
                        retry_5xx=False,
                    )
                except HTTPError as e:
                    if e.status == 200:
                        raise _RangeUnsupported(url) from None
                    raise
            if len(body) != size:
                raise PieceError(
                    f"range GET returned {len(body)} bytes, wanted {size}"
                )
            buf[rel : rel + size] = body
            return size

        results = await asyncio.gather(
            *(fetch_one(rel, size) for rel, size in holes),
            return_exceptions=True,
        )
        errs = [r for r in results if isinstance(r, BaseException)]
        for e in errs:
            if isinstance(e, _RangeUnsupported):
                raise e
        if errs:
            raise errs[0]
        return sum(results)

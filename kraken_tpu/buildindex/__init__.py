"""Build-index: tag -> manifest-digest mapping + cross-cluster replication.

Mirrors uber/kraken ``build-index/`` (tagserver HTTP API, tagstore with
disk cache + backend writeback, durable tag replication to remote
clusters, tag-type dependency resolution) -- upstream paths, unverified;
SURVEY.md SS2.4.
"""

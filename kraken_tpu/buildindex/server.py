"""Build-index tag server + cross-cluster replication.

Mirrors uber/kraken ``build-index/tagserver`` + ``tagreplication``
(put/get tag -> digest, list repo tags, replicate endpoint; durable
replication tasks resolving a tag's blob dependencies so the remote
cluster pre-fetches them) -- upstream paths, unverified; SURVEY.md SS2.4.

Endpoints:

    PUT  /tags/{tag}/digest/{d}              local put
    PUT  /tags/{tag}/digest/{d}/replicate    put + replicate to remotes
    GET  /tags/{tag}                         -> digest string
    GET  /repositories/{repo}/tags           -> JSON list of tag names
    POST /internal/replicate                 {tag, digest, dependencies}
    GET  /health
"""

from __future__ import annotations

import asyncio
import json
import logging
from urllib.parse import quote, unquote

from aiohttp import web

from kraken_tpu.buildindex.tagstore import TagStore
from kraken_tpu.buildindex.tagtype import DependencyResolver
from kraken_tpu.core.digest import Digest, DigestError
from kraken_tpu.persistedretry import Manager as RetryManager, Task
from kraken_tpu.utils.deadline import Deadline
from kraken_tpu.utils.httputil import HTTPClient, base_url

REPLICATE_KIND = "tag_replicate"


class TagServer:
    def __init__(
        self,
        store: TagStore,
        retry: RetryManager | None = None,
        remotes: list[str] | None = None,  # remote build-index addrs
        resolver: DependencyResolver | None = None,
        origin_cluster=None,  # for pre-fetching replicated dependencies
        immutable: bool = False,
    ):
        self.store = store
        self.retry = retry
        self.remotes = remotes or []
        self.resolver = resolver or DependencyResolver(origin_cluster)
        self.origin_cluster = origin_cluster
        # immutable_tags YAML: a tag, once written, can never point at a
        # DIFFERENT digest (re-putting the same digest stays idempotent --
        # retried pushes must not fail). Conflicts answer 409. This is the
        # guarantee that makes aggressive tag caching sound and prevents
        # a re-tagged name from silently changing what hosts run.
        self.immutable = immutable
        # One lock serializes check+put: without it two concurrent PUTs
        # with different digests could both pass the immutability check
        # in the await gap before either write lands.
        self._put_lock = asyncio.Lock()
        self._http = HTTPClient()
        if retry is not None:
            retry.register(REPLICATE_KIND, self._execute_replication)

    async def _checked_put(self, tag: str, d: Digest) -> None:
        """store.put, guarded by the immutability check when enabled.

        The check reads through to the BACKEND (store.get), not just
        local disk: a build-index rescheduled onto a fresh volume must
        still refuse to re-point a tag that exists durably -- the silent
        re-tag is exactly what the feature prevents."""
        if not self.immutable:
            await self.store.put(tag, d)
            return
        ns = tag.rpartition(":")[0] or tag
        async with self._put_lock:
            # get_strict: a backend outage must NOT look like "tag absent"
            # -- that would fail open and allow the silent re-tag this
            # feature exists to prevent. Answer retryable 503 instead.
            try:
                existing = await self.store.get_strict(tag, ns)
            except Exception as e:
                raise web.HTTPServiceUnavailable(
                    text=f"immutability check unavailable: backend error: {e}"
                )
            if existing is not None and existing != d:
                raise web.HTTPConflict(
                    text=f"tag is immutable: {tag} -> {existing}"
                )
            await self.store.put(tag, d)

    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 26)
        r = app.router
        r.add_put("/tags/{tag}/digest/{d}/replicate", self._put_and_replicate)
        r.add_put("/tags/{tag}/digest/{d}", self._put)
        r.add_get("/tags/{tag}", self._get)
        r.add_get("/repositories/{repo}/tags", self._list_repo)
        r.add_get("/internal/tags", self._list_all)
        r.add_post("/internal/replicate", self._recv_replication)
        r.add_get("/health", self._health)
        return app

    def _parse(self, req: web.Request) -> tuple[str, Digest]:
        tag = unquote(req.match_info["tag"])
        try:
            return tag, Digest.from_str(req.match_info["d"])
        except DigestError:
            raise web.HTTPBadRequest(text="malformed digest")

    async def _put(self, req: web.Request) -> web.Response:
        tag, d = self._parse(req)
        await self._checked_put(tag, d)
        return web.Response(status=200)

    async def _put_and_replicate(self, req: web.Request) -> web.Response:
        tag, d = self._parse(req)
        await self._checked_put(tag, d)
        if self.retry is not None:
            deps = await self.resolver.resolve(tag.rpartition(":")[0] or tag, tag, d)
            for remote in self.remotes:
                self.retry.add(
                    Task(
                        kind=REPLICATE_KIND,
                        key=f"{remote}:{tag}",
                        payload={
                            "remote": remote,
                            "tag": tag,
                            "digest": d.hex,
                            "dependencies": [x.hex for x in deps],
                        },
                    )
                )
        return web.Response(status=200)

    async def _execute_replication(self, task: Task) -> None:
        remote = task.payload["remote"]
        tag = task.payload["tag"]
        await self._http.post(
            f"{base_url(remote)}/internal/replicate",
            data=json.dumps(
                {
                    "tag": tag,
                    "digest": task.payload["digest"],
                    "dependencies": task.payload["dependencies"],
                }
            ),
        )

    async def _recv_replication(self, req: web.Request) -> web.Response:
        try:
            doc = await req.json()
            tag = doc["tag"]
            d = Digest.from_hex(doc["digest"])
            deps = [Digest.from_hex(x) for x in doc.get("dependencies", [])]
        except (json.JSONDecodeError, KeyError, ValueError) as e:
            raise web.HTTPBadRequest(text=f"malformed replication: {e}")

        # Pre-fetch dependency blobs into this cluster's origins (repair
        # path pulls them from the remote cluster's backend on miss).
        if self.origin_cluster is not None:
            ns = tag.rpartition(":")[0] or tag
            # One budget for the whole preheat sweep: a dead origin
            # cluster must cost this replication handler one deadline,
            # not len(deps) full client timeouts.
            deadline = Deadline(60.0, component="buildindex-preheat")
            for dep in deps:
                try:
                    await self.origin_cluster.stat(ns, dep, deadline=deadline)
                except Exception:
                    # Best-effort preheat: the repair path covers a cold
                    # dep, but a persistently failing cluster should be
                    # visible in the logs, not silent.
                    logging.getLogger("kraken.buildindex").debug(
                        "dependency preheat failed for %s", dep,
                        exc_info=True,
                    )
        # Two clusters minting the same tag differently is a config
        # error; refusing (409) keeps it visible in the source's retry
        # queue instead of letting last-writer-wins corrupt either side.
        await self._checked_put(tag, d)
        return web.Response(status=200)

    async def _get(self, req: web.Request) -> web.Response:
        tag = unquote(req.match_info["tag"])
        ns = tag.rpartition(":")[0] or tag
        d = await self.store.get(tag, ns)
        if d is None:
            raise web.HTTPNotFound(text="tag not found")
        return web.Response(text=str(d))

    async def _list_repo(self, req: web.Request) -> web.Response:
        repo = unquote(req.match_info["repo"])
        tags = await asyncio.to_thread(self.store.list_local, repo + ":")
        names = [t.rpartition(":")[2] for t in tags]
        return web.json_response(names)

    async def _list_all(self, req: web.Request) -> web.Response:
        tags = await asyncio.to_thread(self.store.list_local, "")
        return web.json_response(tags)

    async def _health(self, req: web.Request) -> web.Response:
        return web.Response(text="ok")


class TagClient:
    """Client for the tag server (agents resolve tags; proxy puts them)."""

    def __init__(self, addr: str, http: HTTPClient | None = None):
        self.addr = addr
        self._http = http or HTTPClient()

    async def put(self, tag: str, d: Digest, replicate: bool = False) -> None:
        suffix = "/replicate" if replicate else ""
        await self._http.put(
            f"{base_url(self.addr)}/tags/{quote(tag, safe='')}/digest/{d.hex}{suffix}",
            ok_statuses=(200,),
        )

    async def get(self, tag: str) -> Digest:
        body = await self._http.get(f"{base_url(self.addr)}/tags/{quote(tag, safe='')}")
        return Digest.parse(body.decode())

    async def list_repo(self, repo: str) -> list[str]:
        body = await self._http.get(
            f"{base_url(self.addr)}/repositories/{quote(repo, safe='')}/tags"
        )
        return json.loads(body)

    async def list_all(self) -> list[str]:
        body = await self._http.get(f"{base_url(self.addr)}/internal/tags")
        return json.loads(body)

    async def close(self) -> None:
        await self._http.close()

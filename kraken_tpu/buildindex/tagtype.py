"""Tag semantics per namespace: dependency resolution for replication.

Mirrors uber/kraken ``build-index/tagtype`` (``docker`` tags depend on the
manifest's referenced blobs so a remote cluster can pre-fetch them;
``default`` tags have no dependencies) -- upstream path, unverified;
SURVEY.md SS2.4.
"""

from __future__ import annotations

import json

from kraken_tpu.core.digest import Digest


def docker_manifest_dependencies(manifest_bytes: bytes) -> list[Digest]:
    """Blob digests referenced by a docker image manifest (config + layers;
    for manifest lists, the per-platform manifest digests)."""
    doc = json.loads(manifest_bytes)
    deps: list[Digest] = []
    if "layers" in doc:  # schema2 manifest
        if "config" in doc:
            deps.append(Digest.parse(doc["config"]["digest"]))
        deps.extend(Digest.parse(l["digest"]) for l in doc["layers"])
    elif "manifests" in doc:  # manifest list
        deps.extend(Digest.parse(m["digest"]) for m in doc["manifests"])
    return deps


class DependencyResolver:
    """Resolve a tag's blob dependency list given its manifest digest.

    ``kind="docker"``: fetch the manifest blob from the origin cluster and
    parse its references. ``kind="default"``: the tagged digest itself is
    the only dependency.
    """

    def __init__(self, origin_cluster=None, kind: str = "docker"):
        if kind not in ("docker", "default"):
            raise ValueError(f"unknown tag type {kind!r}")
        self.kind = kind
        self.origin_cluster = origin_cluster

    async def resolve(self, namespace: str, tag: str, d: Digest) -> list[Digest]:
        if self.kind == "default" or self.origin_cluster is None:
            return [d]
        try:
            manifest = await self.origin_cluster.download(namespace, d)
            return [d, *docker_manifest_dependencies(manifest)]
        except Exception:
            return [d]

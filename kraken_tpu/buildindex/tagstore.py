"""Tag persistence: local disk + async backend writeback.

Mirrors uber/kraken ``build-index/tagstore`` (disk cache, writeback via
persistedretry) -- upstream path, unverified; SURVEY.md SS2.4. A tag is a
``repo:tag`` name mapping to a manifest digest.
"""

from __future__ import annotations

import asyncio
import os
import urllib.parse
from typing import Optional

from kraken_tpu.backend import BlobNotFoundError, Manager as BackendManager
from kraken_tpu.core.digest import Digest
from kraken_tpu.persistedretry import Manager as RetryManager, Task

WRITEBACK_KIND = "tag_writeback"


class _BackendUnavailable(Exception):
    """Transient backend failure during a read-through (NOT proven-absent).

    get() degrades it to None; get_strict propagates it so the
    immutability check can answer a retryable 503."""


class TagStore:
    def __init__(
        self,
        root: str,
        backends: BackendManager | None = None,
        retry: RetryManager | None = None,
    ):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.backends = backends
        self.retry = retry
        if retry is not None and backends is not None:
            retry.register(WRITEBACK_KIND, self._execute_writeback)

    def _path(self, tag: str) -> str:
        return os.path.join(self.root, urllib.parse.quote(tag, safe=""))

    # -- local disk --------------------------------------------------------

    def put_local(self, tag: str, d: Digest) -> None:
        path = self._path(tag)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(d))
        os.replace(tmp, path)

    def get_local(self, tag: str) -> Optional[Digest]:
        try:
            with open(self._path(tag)) as f:
                return Digest.parse(f.read().strip())
        except FileNotFoundError:
            return None

    def list_local(self, prefix: str = "") -> list[str]:
        tags = [urllib.parse.unquote(n) for n in os.listdir(self.root)
                if not n.endswith(".tmp")]
        return sorted(t for t in tags if t.startswith(prefix))

    # -- backend-aware ops -------------------------------------------------

    async def put(self, tag: str, d: Digest, namespace: str = "") -> None:
        """Write locally, then queue durable backend writeback."""
        await asyncio.to_thread(self.put_local, tag, d)
        if self.retry is not None and self.backends is not None:
            if self.backends.try_get_client(namespace or tag) is not None:
                self.retry.add(
                    Task(kind=WRITEBACK_KIND, key=tag,
                         payload={"tag": tag, "namespace": namespace or tag})
                )

    async def get(self, tag: str, namespace: str = "") -> Optional[Digest]:
        """Local first; on miss, fall through to the backend and cache.

        A backend OUTAGE degrades to None (reads are best-effort), but a
        corrupt backend payload (Digest.parse) still raises: a tag that
        exists-but-is-broken must surface as a 5xx, not a definitive
        'tag not found'."""
        try:
            return await self.get_strict(tag, namespace)
        except _BackendUnavailable:
            return None

    async def get_strict(self, tag: str, namespace: str = "") -> Optional[Digest]:
        """Like get(), but only a *proven-absent* tag returns None.

        A backend outage raises instead of returning None, so callers that
        need the distinction (the immutable-tags check) don't fail open:
        a build-index on a fresh volume must not accept a re-point just
        because the backend that holds the truth is temporarily down."""
        local = await asyncio.to_thread(self.get_local, tag)
        if local is not None:
            return local
        if self.backends is None:
            return None
        client = self.backends.try_get_client(namespace or tag)
        if client is None:
            return None
        try:
            raw = await client.download(namespace or tag, tag)
        except BlobNotFoundError:
            return None
        except Exception as e:
            raise _BackendUnavailable(str(e)) from e
        d = Digest.parse(raw.decode().strip())
        try:
            await asyncio.to_thread(self.put_local, tag, d)
        except OSError:
            # Cache write is best-effort: a full/read-only volume must
            # not turn a successfully fetched tag into an error.
            pass
        return d

    async def _execute_writeback(self, task: Task) -> None:
        tag = task.payload["tag"]
        ns = task.payload["namespace"]
        d = self.get_local(tag)
        if d is None:
            return
        client = self.backends.get_client(ns)
        await client.upload(ns, tag, str(d).encode())
